"""Serving smoke bench: continuous batching vs static whole-batch generate.

Synthetic-arrivals ladder (Poisson interarrivals) over a mixed-length
workload — prompts of varying length, generation lengths skewed the way real
traffic is (many short, a few long). The static baseline is what the repo
had before `paddle_tpu.serving`: collect B arrived requests, pad prompts to
one bucket, run ONE whole-batch `generate_from_params` for the worst-case
max_new_tokens (so it keeps a single cached executable — the most generous
static baseline), tokens available only when the whole batch finishes. The
continuous engine admits at iteration boundaries and recycles a slot the
moment its request finishes.

Reported per rung: useful tokens/s, p50/p99 TTFT, wall time, speedup.
Quick mode (default) runs one backlogged rung; --full runs the arrival-rate
ladder. Gate: continuous batching >= 1.5x static tokens/s on the mixed
workload (asserted by tests/test_serving.py::test_smoke_bench_* [slow]).

Usage:  JAX_PLATFORMS=cpu python tools_serving_smoke.py [--full]
"""
import json
import sys
import time

import numpy as np

import paddle_tpu  # noqa: F401  (platform/init side effects)
import jax
from paddle_tpu import serving
from paddle_tpu.models.generation import generate_from_params
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import init_gpt_params

SLOTS = 8
PROMPT_BUCKET = 64
MAX_NEW = 64
SMAX = 160


def _model(quick):
    # big enough that a decode step dominates host dispatch on CPU, small
    # enough that the quick rung finishes in tens of seconds
    cfg = GPTConfig(vocab_size=512, hidden_size=512 if quick else 768,
                    num_layers=4, num_heads=8, max_seq_len=SMAX,
                    dropout=0.0, use_flash=False, compute_dtype="float32",
                    remat=False)
    return init_gpt_params(cfg, jax.random.key(0)), cfg


def _workload(n, rate, rng):
    """n requests: Poisson arrivals at `rate` req/s, mixed prompt lengths,
    generation lengths skewed short with a heavy tail (every batch of the
    static baseline ends up hostage to one long request)."""
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    reqs = []
    for i in range(n):
        plen = int(rng.integers(8, PROMPT_BUCKET))
        new = MAX_NEW if i % SLOTS == 0 else int(rng.integers(4, 12))
        reqs.append({"arrival": float(arrivals[i]),
                     "prompt": rng.integers(0, 512, plen),
                     "max_new": new})
    return reqs


def run_static(params, cfg, work):
    """FCFS batches of SLOTS over ARRIVED requests; one whole-batch generate
    per batch at the shared worst-case shape (single cached executable)."""
    # warmup (compile) outside the clock
    warm = np.zeros((SLOTS, PROMPT_BUCKET), np.int32)
    generate_from_params(params, warm, cfg, max_new_tokens=MAX_NEW)._data.block_until_ready()

    t0 = time.perf_counter()
    ttfts, useful = [], 0
    i = 0
    while i < len(work):
        batch = work[i:i + SLOTS]
        i += SLOTS
        # static serving cannot start before its whole batch has arrived
        gate = max(b["arrival"] for b in batch)
        now = time.perf_counter() - t0
        if now < gate:
            time.sleep(gate - now)
        ids = np.zeros((len(batch), PROMPT_BUCKET), np.int32)
        for r, b in enumerate(batch):
            ids[r, :len(b["prompt"])] = b["prompt"]
        out = generate_from_params(params, ids, cfg, max_new_tokens=MAX_NEW)
        out._data.block_until_ready()
        done = time.perf_counter() - t0
        for b in batch:
            useful += b["max_new"]            # tokens the user asked for
            ttfts.append(done - b["arrival"])  # tokens exist only at the end
    wall = time.perf_counter() - t0
    return {"tokens": useful, "wall_s": round(wall, 3),
            "tokens_per_s": round(useful / wall, 1),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 3),
            "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 3)}


def run_continuous(params, cfg, work):
    eng = serving.Engine(params=params, config=cfg, num_slots=SLOTS,
                         max_seq_len=SMAX, prefill_buckets=(PROMPT_BUCKET,),
                         max_queue=len(work) + 1)
    # warmup both executables outside the clock
    eng.generate([np.arange(4)], max_new_tokens=2)

    t0 = time.perf_counter()
    reqs = [serving.Request(w["prompt"], max_new_tokens=w["max_new"])
            for w in work]
    pending = list(zip(work, reqs))
    done = {}
    while pending or eng.queue_depth or eng.active_slots:
        now = time.perf_counter() - t0
        while pending and pending[0][0]["arrival"] <= now:
            eng.submit(pending.pop(0)[1])
        if not (eng.queue_depth or eng.active_slots):
            time.sleep(max(0.0, pending[0][0]["arrival"] - now))
            continue
        eng.step()
        done.update(eng.pop_results())
    wall = time.perf_counter() - t0
    useful = sum(len(r.tokens) for r in done.values())
    # TTFT vs ARRIVAL time (submit_t is deferred to the arrival instant)
    ttfts = [done[r.request_id].ttft for r in reqs]
    return {"tokens": useful, "wall_s": round(wall, 3),
            "tokens_per_s": round(useful / wall, 1),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 3),
            "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 3)}


def run_ladder(quick=True):
    params, cfg = _model(quick)
    n = 24 if quick else 48
    rates = [1e9] if quick else [2.0, 8.0, 1e9]   # req/s; 1e9 = backlogged
    out = []
    for rate in rates:
        work = _workload(n, rate, np.random.default_rng(0))
        static = run_static(params, cfg, work)
        cont = run_continuous(params, cfg, work)
        rung = {
            "bench": "serving_smoke", "requests": n,
            "rate_req_s": None if rate > 1e6 else rate,
            "backend": jax.default_backend(),
            "static": static, "continuous": cont,
            "speedup": round(cont["tokens_per_s"] / static["tokens_per_s"], 2),
            "ttft_p50_ratio": round(
                static["ttft_p50_s"] / max(cont["ttft_p50_s"], 1e-9), 1),
        }
        print(json.dumps(rung))
        out.append(rung)
    return out


if __name__ == "__main__":
    results = run_ladder(quick="--full" not in sys.argv)
    # tokens/s gates the CAPACITY-bound (backlogged) rungs; in the
    # arrival-limited rungs both systems idle between requests and the
    # meaningful win is TTFT (tokens stream per iteration instead of at
    # whole-batch completion)
    cap = min(r["speedup"] for r in results if r["rate_req_s"] is None)
    ttft = max(r["ttft_p50_ratio"] for r in results)
    print(f"# continuous batching vs static whole-batch: backlogged "
          f"speedup {cap:.2f}x "
          f"({'PASS' if cap >= 1.5 else 'FAIL'} >= 1.5x gate), "
          f"best p50-TTFT ratio {ttft:.1f}x")
