"""Serving smoke bench: continuous batching vs static whole-batch generate,
and (run_paged_rung) the block-paged KV layout vs the pooled layout.

Synthetic-arrivals ladder (Poisson interarrivals) over a mixed-length
workload — prompts of varying length, generation lengths skewed the way real
traffic is (many short, a few long). The static baseline is what the repo
had before `paddle_tpu.serving`: collect B arrived requests, pad prompts to
one bucket, run ONE whole-batch `generate_from_params` for the worst-case
max_new_tokens (so it keeps a single cached executable — the most generous
static baseline), tokens available only when the whole batch finishes. The
continuous engine admits at iteration boundaries and recycles a slot the
moment its request finishes.

Reported per rung: useful tokens/s, p50/p99 TTFT, wall time, speedup.
Quick mode (default) runs one backlogged rung; --full runs the arrival-rate
ladder. Gate: continuous batching >= 1.5x static tokens/s on the mixed
workload (asserted by tests/test_serving.py::test_smoke_bench_* [slow]).

Usage:  JAX_PLATFORMS=cpu python tools_serving_smoke.py [--full]
"""
import json
import os
import sys
import time

if "--mp" in sys.argv or "--mp-det" in sys.argv:
    # the mp ladder needs the 8-virtual-device CPU mesh (same rig as
    # tests/conftest.py); XLA reads this at first backend init, which
    # must not have happened yet
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

import paddle_tpu  # noqa: F401  (platform/init side effects)
import jax
from paddle_tpu import serving
from paddle_tpu.models.generation import generate_from_params
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import init_gpt_params

SLOTS = 8
PROMPT_BUCKET = 64
MAX_NEW = 64
SMAX = 160


def _model(quick):
    # big enough that a decode step dominates host dispatch on CPU, small
    # enough that the quick rung finishes in tens of seconds
    cfg = GPTConfig(vocab_size=512, hidden_size=512 if quick else 768,
                    num_layers=4, num_heads=8, max_seq_len=SMAX,
                    dropout=0.0, use_flash=False, compute_dtype="float32",
                    remat=False)
    return init_gpt_params(cfg, jax.random.key(0)), cfg


def _workload(n, rate, rng):
    """n requests: Poisson arrivals at `rate` req/s, mixed prompt lengths,
    generation lengths skewed short with a heavy tail (every batch of the
    static baseline ends up hostage to one long request)."""
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    reqs = []
    for i in range(n):
        plen = int(rng.integers(8, PROMPT_BUCKET))
        new = MAX_NEW if i % SLOTS == 0 else int(rng.integers(4, 12))
        reqs.append({"arrival": float(arrivals[i]),
                     "prompt": rng.integers(0, 512, plen),
                     "max_new": new})
    return reqs


def run_static(params, cfg, work):
    """FCFS batches of SLOTS over ARRIVED requests; one whole-batch generate
    per batch at the shared worst-case shape (single cached executable)."""
    # warmup (compile) outside the clock
    warm = np.zeros((SLOTS, PROMPT_BUCKET), np.int32)
    generate_from_params(params, warm, cfg, max_new_tokens=MAX_NEW)._data.block_until_ready()

    t0 = time.perf_counter()
    ttfts, useful = [], 0
    i = 0
    while i < len(work):
        batch = work[i:i + SLOTS]
        i += SLOTS
        # static serving cannot start before its whole batch has arrived
        gate = max(b["arrival"] for b in batch)
        now = time.perf_counter() - t0
        if now < gate:
            time.sleep(gate - now)
        ids = np.zeros((len(batch), PROMPT_BUCKET), np.int32)
        for r, b in enumerate(batch):
            ids[r, :len(b["prompt"])] = b["prompt"]
        out = generate_from_params(params, ids, cfg, max_new_tokens=MAX_NEW)
        out._data.block_until_ready()
        done = time.perf_counter() - t0
        for b in batch:
            useful += b["max_new"]            # tokens the user asked for
            ttfts.append(done - b["arrival"])  # tokens exist only at the end
    wall = time.perf_counter() - t0
    return {"tokens": useful, "wall_s": round(wall, 3),
            "tokens_per_s": round(useful / wall, 1),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 3),
            "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 3)}


def run_continuous(params, cfg, work):
    # this ladder gates the PR 5 continuous-vs-static comparison on the
    # POOLED layout; the paged layout has its own rung (run_paged_rung)
    eng = serving.Engine(params=params, config=cfg, num_slots=SLOTS,
                         max_seq_len=SMAX, prefill_buckets=(PROMPT_BUCKET,),
                         kv_layout="pooled", max_queue=len(work) + 1)
    # warmup both executables outside the clock
    eng.generate([np.arange(4)], max_new_tokens=2)

    t0 = time.perf_counter()
    reqs = [serving.Request(w["prompt"], max_new_tokens=w["max_new"])
            for w in work]
    pending = list(zip(work, reqs))
    done = {}
    while pending or eng.queue_depth or eng.active_slots:
        now = time.perf_counter() - t0
        while pending and pending[0][0]["arrival"] <= now:
            eng.submit(pending.pop(0)[1])
        if not (eng.queue_depth or eng.active_slots):
            time.sleep(max(0.0, pending[0][0]["arrival"] - now))
            continue
        eng.step()
        done.update(eng.pop_results())
    wall = time.perf_counter() - t0
    useful = sum(len(r.tokens) for r in done.values())
    # TTFT vs ARRIVAL time (submit_t is deferred to the arrival instant)
    ttfts = [done[r.request_id].ttft for r in reqs]
    return {"tokens": useful, "wall_s": round(wall, 3),
            "tokens_per_s": round(useful / wall, 1),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 3),
            "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 3)}


# ---------------------------------------------------------------------------
# paged vs pooled KV layout (PR 7): same KV memory, mixed-length workload


def _paged_model(deterministic):
    if deterministic:   # tiny: tier-1 runs this without wall-clock gates
        cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=128, dropout=0.0,
                        use_flash=False, compute_dtype="float32", remat=False)
    else:
        # decode serving is dispatch/latency-bound (tiny per-step compute),
        # on TPU and CPU alike — hidden=256 keeps the CPU rung in that
        # regime so the batching/occupancy effects are what gets measured
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=1024, dropout=0.0,
                        use_flash=False, compute_dtype="float32", remat=False)
    return init_gpt_params(cfg, jax.random.key(0)), cfg


def _mixed_workload(n, rate, rng, short_pl, long_pl, xl_pl, short_new,
                    long_new, xl_new, vocab, sys_len=0, tmpl_len=0):
    """Mixed-length traffic, Poisson arrivals at `rate` req/s (rate=None
    -> backlogged: everything queued at t=0): mostly short turns, every
    3rd request long, every 6th an XL long-tail request. The tail is what
    breaks the pooled layout twice over — every slot must reserve
    worst-case Smax (so the tail sets the whole engine's batch size), and
    each long admission is a monolithic prefill during which no slot
    decodes. Long/XL prompts share a `sys_len`-token system prompt and
    short ones a `tmpl_len`-token chat template (the millions-of-users
    traffic shape) — the paged engine's prefix cache serves those tokens
    from shared pages; the pooled engine recomputes them every request."""
    arrivals = (np.zeros(n) if rate is None
                else np.cumsum(rng.exponential(1.0 / rate, n)))
    sys_p = rng.integers(0, vocab, sys_len)
    # the XL class shares a LONG context (RAG document / agent system
    # prompt reused across queries) — the prefix cache's marquee case
    sys_xl = rng.integers(0, vocab, (xl_pl[0] * 3) // 4)
    tmpl = rng.integers(0, vocab, tmpl_len)
    work = []
    for i in range(n):
        if i % 6 == 5:
            pl, nw, head, long = xl_pl, xl_new, sys_xl, True
        elif i % 3 == 2:
            pl, nw, head, long = long_pl, long_new, sys_p, True
        else:
            pl, nw, head, long = short_pl, short_new, tmpl, False
        plen = int(rng.integers(*pl))
        new = int(rng.integers(*nw))
        prompt = np.concatenate(
            [head, rng.integers(0, vocab, max(plen - len(head), 1))])
        work.append({"arrival": float(arrivals[i]), "long": long,
                     "prompt": prompt, "max_new": new})
    return work


def _drive(eng, work):
    """Submit at arrival times, step to drain; returns (per-request token
    lists in workload order, wall seconds, per-request emission stamps)."""
    stamps = {}

    def cb(r, t):
        stamps.setdefault(r.request_id, []).append(time.perf_counter())

    reqs = [serving.Request(w["prompt"], max_new_tokens=w["max_new"],
                            on_token=cb) for w in work]
    pending = list(zip(work, reqs))
    done = {}
    t0 = time.perf_counter()
    while pending or eng.queue_depth or eng.active_slots:
        now = time.perf_counter() - t0
        while pending and pending[0][0]["arrival"] <= now:
            eng.submit(pending.pop(0)[1])
        if not (eng.queue_depth or eng.active_slots):
            time.sleep(max(0.0, pending[0][0]["arrival"] - now))
            continue
        eng.step()
        done.update(eng.pop_results())
    wall = time.perf_counter() - t0
    tokens = [done[r.request_id].tokens for r in reqs]
    return tokens, wall, [stamps.get(r.request_id, []) for r in reqs]


def _intertoken_p99(stamps, work):
    """p99 gap between consecutive emitted tokens of SHORT requests — the
    inter-token latency a user streaming a short answer sees while long
    prefills come and go."""
    gaps = []
    for ts, w in zip(stamps, work):
        if not w["long"]:
            gaps.extend(np.diff(ts))
    return float(np.percentile(gaps, 99)) if gaps else 0.0


def run_paged_rung(quick=True, deterministic=False, rate=None, repeats=3):
    """Pooled vs paged at EQUAL KV memory. Pooled reserves worst-case
    Smax per slot (the XL tail sets it), so its batch collapses to a few
    slots and each long admission is a monolithic prefill stall; paged
    spends the same bytes on pages — admission bounded by ACTUAL request
    footprints, hot prompt prefixes served from shared pages, prefill
    chunks interleaved with decode. Gates (timed mode): paged >= 1.3x
    tokens/s backlogged, inter-token p99 of short requests not regressed,
    plus a request that only fits in pages (prompt+new > pooled Smax).
    Each engine is driven `repeats` times with fresh engine state
    (executables stay jit-cached) and the best run is scored — the
    standard guard against interference on a shared host."""
    from paddle_tpu import profiler
    params, cfg = _paged_model(deterministic)
    if deterministic:
        smax, slots, ps, pslots = 48, 4, 8, 16
        short_pl, long_pl, xl_pl = (3, 15), (20, 33), (34, 41)
        short_new, long_new, xl_new = (3, 7), (4, 9), (4, 8)
        sys_len, tmpl_len = 16, 0
        buckets = (short_pl[1] - 1, (smax + 1) // 2, smax)
        n = 10
    else:
        # Smax is set by the LONGEST admissible request (the XL tail) —
        # the pooled layout must reserve it for EVERY slot, so the same
        # KV bytes buy it 4 worst-case slots while the paged layout runs
        # 24 actual-footprint slots
        smax, slots, ps, pslots = 768, 4, 16, 24
        short_pl, long_pl, xl_pl = (18, 49), (96, 129), (520, 641)
        short_new, long_new, xl_new = (24, 49), (40, 64), (16, 33)
        sys_len, tmpl_len = 96, 16
        buckets = (short_pl[1] - 1, 192, smax)
        n = 72 if quick else 144
    num_pages = slots * smax // ps + 1      # memory-equal (+trash page)
    work = _mixed_workload(n, rate, np.random.default_rng(0), short_pl,
                           long_pl, xl_pl, short_new, long_new, xl_new,
                           cfg.vocab_size, sys_len=sys_len,
                           tmpl_len=tmpl_len)

    chunk = ps if deterministic else 4 * ps

    def build():
        """Fresh engine pair per trial (the jitted executables are shared
        across engines per shape, so rebuilds are cheap): warm every
        prefill bucket / chunk-ladder rung, then a throwaway mini-drive
        over one request of every class so hot prefixes are cached —
        steady-state serving runs with warm caches."""
        pooled = serving.Engine(params=params, config=cfg, num_slots=slots,
                                max_seq_len=smax, kv_layout="pooled",
                                prefill_buckets=buckets, max_queue=n + 2)
        # same KV bytes, spent on pages instead of worst-case slots —
        # admission bounded by each request's ACTUAL footprint
        paged = serving.Engine(params=params, config=cfg,
                               num_slots=pslots, max_seq_len=smax,
                               kv_layout="paged", page_size=ps,
                               num_pages=num_pages, prefill_chunk=chunk,
                               max_queue=n + 2)
        warm_lens = sorted({ps + 1, *paged._chunk_ladder} |
                           {b - 2 for b in pooled.scheduler.buckets})
        for eng in (pooled, paged):
            eng.generate([np.arange(1, ln + 1) for ln in warm_lens],
                         max_new_tokens=2)
            if eng is paged:
                eng.pool.clear_cache()   # drop the warmup prompts' pins
            _drive(eng, work[:6])        # hot prefixes cached
        return pooled, paged

    if deterministic:
        repeats = 1
    best = {}
    outputs_match = True
    for _ in range(max(1, repeats)):
        pooled, paged = build()
        trial = {}
        for name, eng in (("pooled", pooled), ("paged", paged)):
            profiler.reset_serving_counters()
            toks, wall, stamps = _drive(eng, work)
            trial[name] = (toks, wall, stamps, profiler.serving_counters())
        outputs_match = outputs_match and \
            trial["pooled"][0] == trial["paged"][0]
        for name, t in trial.items():
            if name not in best or t[1] < best[name][1]:
                best[name] = t
    pooled_toks, pooled_wall, pooled_stamps, pc = best["pooled"]
    paged_toks, paged_wall, paged_stamps, gc = best["paged"]

    useful = sum(len(t) for t in paged_toks)
    # capacity demo (outside the timed section): a request whose
    # prompt+max_new exceeds the pooled layout's per-slot Smax serves fine
    # from the same page pool with a longer virtual window
    cap_prompt = np.arange(1, smax)          # smax-1 + 16 > smax
    try:
        pooled.submit(serving.Request(cap_prompt, max_new_tokens=16))
        cap_only_paged = False
    except ValueError:
        cap_eng = serving.Engine(
            params=params, config=cfg, num_slots=slots,
            max_seq_len=min(2 * smax, cfg.max_seq_len), kv_layout="paged",
            page_size=ps, num_pages=num_pages, prefill_chunk=chunk)
        res = cap_eng.run([serving.Request(cap_prompt, max_new_tokens=16)])
        cap_only_paged = all(len(r.tokens) == 16 for r in res.values())

    out = {
        "bench": "serving_paged_smoke", "requests": n,
        "rate_req_s": rate, "backend": jax.default_backend(),
        "page_size": ps, "num_pages": num_pages,
        "outputs_match": outputs_match and pooled_toks == paged_toks,
        "capacity_only_paged": cap_only_paged,
        "pooled": {
            "slots": slots, "smax": smax, "wall_s": round(pooled_wall, 3),
            "tokens_per_s": round(sum(len(t) for t in pooled_toks)
                                  / pooled_wall, 1),
            "intertoken_p99_s": round(_intertoken_p99(pooled_stamps, work), 4),
            "prefill_waste_mean": round(pc["prefill_waste_mean"], 1),
            "prefill_waste_max": pc["prefill_padded_max"],
        },
        "paged": {
            "slots": pslots, "wall_s": round(paged_wall, 3),
            "tokens_per_s": round(useful / paged_wall, 1),
            "intertoken_p99_s": round(_intertoken_p99(paged_stamps, work), 4),
            "prefill_waste_mean": round(gc["prefill_waste_mean"], 1),
            "prefill_waste_max": gc["prefill_padded_max"],
            "page_occupancy": round(gc["page_occupancy"], 3),
            "prefix_hit_rate": round(gc["prefix_hit_rate"], 3),
            "chunk_steps": gc["chunk_steps"], "cow_copies": gc["cow_copies"],
        },
    }
    out["speedup"] = round(out["paged"]["tokens_per_s"]
                           / max(out["pooled"]["tokens_per_s"], 1e-9), 2)
    print(json.dumps(out))
    return out


def run_mp_rung(deterministic=False, backends=("gspmd", "ring"),
                mps=(2, 4), repeats=2):
    """Tensor-parallel serving ladder at MEMORY-EQUAL per-chip sizing:
    the single-chip engine gets a KV budget of P0 pages / S0 slots; an
    mp-degree engine spends the SAME per-chip bytes, which at 1/mp
    per-chip KV cost buys mp x the pages and slots — the capacity lever
    of sharding. Reported per rung: tokens/s (backlogged), inter-token
    p99, per-chip KV bytes, wire bytes and fused-dispatch counts.

    Timed rungs run gspmd/ring (real XLA collectives over the 8-virtual-
    device CPU mesh; on TPU the same code times all three). The fused
    rung runs Pallas kernels in INTERPRET mode on CPU — an emulation
    whose wall time is meaningless — so it is scored for parity + fused
    dispatch counts on the deterministic model only.

    Gate (tests/test_mp_serving.py, slow): best mp rung >= 1.4x
    single-chip tokens/s, outputs bitwise identical everywhere."""
    from paddle_tpu import profiler
    from paddle_tpu.ops.pallas_kernels import fused_collectives as fc
    if deterministic:
        cfg = GPTConfig(vocab_size=96, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=128, dropout=0.0,
                        use_flash=False, compute_dtype="float32",
                        remat=False)
        smax, ps, S0, n, newr, repeats = 48, 8, 2, 8, (3, 7), 1
    else:
        # per-chip compute big enough that sharding it wins on CPU too;
        # S0=2 is the honest memory-equal regime — a model sized to fill
        # one chip's HBM leaves almost no single-chip KV room
        cfg = GPTConfig(vocab_size=512, hidden_size=384, num_layers=4,
                        num_heads=8, max_seq_len=512, dropout=0.0,
                        use_flash=False, compute_dtype="float32",
                        remat=False)
        smax, ps, S0, n, newr = 256, 16, 2, 40, (8, 20)
    params = init_gpt_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    work = [{"arrival": 0.0, "long": False,
             "prompt": rng.integers(0, cfg.vocab_size,
                                    int(rng.integers(4, smax // 6))),
             "max_new": int(rng.integers(*newr))} for _ in range(n)]
    P0 = S0 * smax // ps + 1

    def build(mp, backend):
        kw = dict(params=params, config=cfg, num_slots=S0 * max(mp, 1),
                  max_seq_len=smax, page_size=ps,
                  num_pages=(P0 - 1) * max(mp, 1) + 1,
                  prefill_chunk=2 * ps, max_queue=n + 2)
        if mp > 1:
            kw.update(mp=mp, comm_backend=backend)
        return serving.Engine(**kw)

    rungs = []
    base_tokens = None
    ladder = [(1, "gspmd")] + [(mp, b) for b in backends for mp in mps]
    for mp, backend in ladder:
        if backend == "fused" and not deterministic \
                and jax.default_backend() != "tpu":
            # interpret-mode emulation: parity-only, timed on TPU
            rungs.append({"mp": mp, "backend": "fused",
                          "skipped": "interpret-mode timing meaningless "
                                     "on CPU (run --mp-det for parity + "
                                     "dispatch counts)"})
            continue
        fc.reset_trace_counts()
        build(mp, backend).generate(
            [np.arange(1, ps + 2), np.arange(1, 2 * ps + 2)],
            max_new_tokens=2)                      # warm both rungs
        traces = dict(fc.trace_counts())           # trace-time kernel audit
        best = None
        for _ in range(max(1, repeats)):
            eng = build(mp, backend)
            profiler.reset_serving_counters()
            toks, wall, stamps = _drive(eng, work)
            c = profiler.serving_counters()
            if best is None or wall < best[1]:
                best = (toks, wall, stamps, c, eng.kv_shard_bytes())
        toks, wall, stamps, c, shard_bytes = best
        if base_tokens is None:
            base_tokens = toks
        rungs.append({
            "mp": mp, "backend": backend,
            "tokens_per_s": round(sum(len(t) for t in toks) / wall, 1),
            "intertoken_p99_s": round(_intertoken_p99(stamps, work), 4),
            "slots": S0 * max(mp, 1), "kv_bytes_per_chip": shard_bytes,
            "wire_mb": round(c["mp_wire_bytes"] / 1e6, 2),
            "fused_dispatches": c["mp_fused_dispatches"],
            "kernel_traces": traces,
            "outputs_match": toks == base_tokens,
        })
        print(json.dumps({"bench": "serving_mp_smoke", **rungs[-1]}))
    out = {"bench": "serving_mp_smoke", "requests": n,
           "backend": jax.default_backend(), "deterministic": deterministic,
           "rungs": rungs}
    timed = [r for r in rungs if "tokens_per_s" in r]
    if len(timed) > 1:
        base = timed[0]["tokens_per_s"]
        out["best_speedup"] = round(
            max(r["tokens_per_s"] for r in timed[1:]) / base, 2)
    out["outputs_match"] = all(r.get("outputs_match", True) for r in rungs)
    print(json.dumps({k: v for k, v in out.items() if k != "rungs"}))
    return out


def run_quant_rung(quick=True, deterministic=False, rate=None, repeats=3):
    """Quantized serving at EQUAL KV memory (serving/quant.py): the fp
    engine gets a page budget; the int8-weight + int8-KV engine spends
    the SAME bytes on 4x the pages (fp32 -> int8) and scales its slot
    count with the capacity, so backlogged traffic decodes in a larger
    batch per dispatch. Reported: tokens/s, slots, per-chip KV bytes and
    bytes/token by dtype, max logit drift vs the fp forward, and greedy
    task-level agreement. Gate (timed mode): slots x tokens/s
    (capacity_throughput) strictly UP under quantization with drift
    bounded — the raw capacity-per-chip lever."""
    from paddle_tpu import profiler
    from paddle_tpu.serving.quant import QuantSpec, calibrate, \
        max_logit_drift
    params, cfg = _paged_model(deterministic)
    if deterministic:
        smax, ps, slots, qslots = 48, 8, 3, 6
        short_pl, long_pl, xl_pl = (3, 15), (20, 33), (34, 41)
        short_new, long_new, xl_new = (3, 7), (4, 9), (4, 8)
        n = 10
    else:
        smax, ps, slots, qslots = 512, 16, 6, 24
        short_pl, long_pl, xl_pl = (18, 49), (96, 129), (320, 441)
        short_new, long_new, xl_new = (24, 49), (40, 64), (16, 33)
        n = 60 if quick else 120
    fp_pages = slots * smax // ps + 1
    item = np.dtype(cfg.compute_dtype or "float32").itemsize
    q_pages = (fp_pages - 1) * item + 1     # same bytes at 1 byte/elem
    chunk = ps if deterministic else 4 * ps
    work = _mixed_workload(n, rate, np.random.default_rng(0), short_pl,
                           long_pl, xl_pl, short_new, long_new, xl_new,
                           cfg.vocab_size, sys_len=16, tmpl_len=0)
    # PTQ calibration through the quantization package: per-channel
    # weight scales + per-layer KV clip ranges from a token sample
    spec = calibrate(params, cfg,
                     sample_ids=np.arange(1, min(smax, 64)) % cfg.vocab_size)
    drift, logit_scale = max_logit_drift(
        params, cfg, QuantSpec("int8", "int8", kv_k_clip=spec.kv_k_clip,
                               kv_v_clip=spec.kv_v_clip),
        list(range(1, min(smax, 48))), page_size=ps)
    serving.metrics.observe_logit_drift(drift)

    def build(quant):
        eng = serving.Engine(
            params=params, config=cfg,
            num_slots=qslots if quant else slots, max_seq_len=smax,
            page_size=ps, num_pages=q_pages if quant else fp_pages,
            prefill_chunk=chunk, max_queue=n + 2,
            quant=spec if quant else None)
        warm = sorted({ps + 1, *eng._chunk_ladder})
        eng.generate([np.arange(1, ln + 1) for ln in warm],
                     max_new_tokens=2)
        eng.pool.clear_cache()
        _drive(eng, work[:4])
        return eng

    if deterministic:
        repeats = 1
    best = {}
    toks_by = {}
    for _ in range(max(1, repeats)):
        for name, quant in (("fp", False), ("quant", True)):
            eng = build(quant)
            profiler.reset_serving_counters()
            toks, wall, _stamps = _drive(eng, work)
            toks_by.setdefault(name, toks)
            # each config is deterministic vs itself across trials
            assert toks_by[name] == toks, f"{name} nondeterministic"
            rec = {
                "slots": eng.num_slots, "pages": eng.pool.num_pages - 1,
                "kv_pool_bytes": eng.kv_shard_bytes(),
                "kv_bytes_per_token": eng.kv_bytes_per_token(),
                "tokens_per_s": round(sum(len(t) for t in toks) / wall, 1),
                "wall_s": round(wall, 3),
            }
            rec["capacity_throughput"] = round(
                rec["slots"] * rec["tokens_per_s"], 1)
            if name not in best or rec["wall_s"] < best[name]["wall_s"]:
                best[name] = rec
    # greedy task-level drift: fraction of positions where the quantized
    # stream emits the fp engine's token
    total = sum(len(t) for t in toks_by["fp"])
    agree = sum(a == b for ft, qt in zip(toks_by["fp"], toks_by["quant"])
                for a, b in zip(ft, qt))
    # capacity demo (outside the timed section): at a TIGHT byte budget
    # (one worst-case context's fp32 pages minus one) a whole-lifetime
    # smax request can NEVER fit the fp pool — the same bytes as int8
    # pages hold it with 3x room to spare
    demo_pages = smax // ps                 # usable = demo_pages - 1
    cap_prompt = np.arange(1, smax - 8 + 1)     # lifetime = smax exactly
    fp_demo = serving.Engine(params=params, config=cfg, num_slots=2,
                             max_seq_len=smax, page_size=ps,
                             num_pages=demo_pages, prefill_chunk=chunk)
    try:
        fp_demo.submit(serving.Request(cap_prompt, max_new_tokens=8))
        cap_only_quant = False
    except ValueError:
        q_demo = serving.Engine(
            params=params, config=cfg, num_slots=2, max_seq_len=smax,
            page_size=ps, num_pages=(demo_pages - 1) * item + 1,
            prefill_chunk=chunk, quant=spec)
        res = q_demo.run([serving.Request(cap_prompt, max_new_tokens=8)])
        cap_only_quant = all(len(r.tokens) == 8 for r in res.values())
    out = {
        "bench": "serving_quant_smoke", "requests": n,
        "backend": jax.default_backend(), "page_size": ps,
        "weight_dtype": "int8", "kv_dtype": "int8",
        "max_logit_drift": round(drift, 6),
        "max_abs_logit": round(logit_scale, 4),
        "greedy_agreement": round(agree / max(total, 1), 3),
        "capacity_only_quant": cap_only_quant,
        "fp": best["fp"], "quant": best["quant"],
    }
    out["capacity_throughput_ratio"] = round(
        best["quant"]["capacity_throughput"]
        / max(best["fp"]["capacity_throughput"], 1e-9), 2)
    print(json.dumps(out))
    return out


def run_spec_rung(quick=True, deterministic=False, rate=None, repeats=3):
    """Speculative multi-token decoding (serving speculate_k): a k-token
    self-draft pass plus ONE fused [B,k+1] verify per boundary, vs the
    plain one-token decode loop on the SAME paged engine config.

    Deterministic mode (tier-1): for each dtype config — fp32 engine with
    int8 self-draft, fp32 engine with a shallow-layer draft, int8 engine
    with the degenerate self-draft — the speculative streams (greedy AND
    sampled, mixed in one batch) must be BITWISE the plain engine's, the
    self-draft accept rate sane, and the draft/verify executables FROZEN
    under a second traffic wave (zero new traces: admission order, slot
    churn and accept/reject mixes all replay the same two executables).

    Timed mode (slow): backlogged greedy traffic, plain vs speculate_k=4.
    Gate: tokens/s >= 1.3x plain with tokens_per_dispatch > 1.5 — each
    draft+verify dispatch pair must amortize over multiple emitted tokens
    for speculation to beat the dispatch-bound one-token loop."""
    from paddle_tpu import profiler
    from paddle_tpu.serving.quant import QuantSpec
    if deterministic:
        params, cfg = _paged_model(True)
        smax, ps, slots = 48, 8, 4
        short_pl, long_pl, xl_pl = (3, 15), (20, 33), (34, 41)
        short_new, long_new, xl_new = (3, 7), (4, 9), (4, 8)
        n, chunk, k = 10, ps, 4
    else:
        # the speculation win is DISPATCH amortization: k+1 tokens ride one
        # draft + one verify dispatch instead of k+1 decode dispatches. On
        # TPU a decode step is memory-bound and the [B,k+1] verify costs
        # ~one decode step; on CPU the per-lane verify reads and the int8
        # draft are real COMPUTE, so the rung must sit where host dispatch
        # dominates per-step compute — a small model at small batch, the
        # latency-bound serving corner where speculation is used in anger
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=8, max_seq_len=512, dropout=0.0,
                        use_flash=False, compute_dtype="float32",
                        remat=False)
        params = init_gpt_params(cfg, jax.random.key(0))
        # small batch + decode-heavy traffic: each boundary's dispatch is
        # shared by few slots, so per-token dispatch overhead is at its
        # worst — exactly the regime speculation collapses
        smax, ps, slots = 256, 16, 2
        short_pl, long_pl, xl_pl = (8, 25), (8, 33), (8, 33)
        short_new, long_new, xl_new = (32, 65), (48, 81), (64, 97)
        n, chunk, k = (24 if quick else 48), 4 * ps, 4
    pages = slots * smax // ps + 1
    work = _mixed_workload(n, rate, np.random.default_rng(0), short_pl,
                           long_pl, xl_pl, short_new, long_new, xl_new,
                           cfg.vocab_size, sys_len=2 * ps, tmpl_len=0)

    def build(spec_k, source=None, quant=None):
        # spec_k=0 is an EXPLICIT off (wins over any ambient flags) so the
        # baseline engine is the pre-speculation engine byte for byte
        return serving.Engine(params=params, config=cfg, num_slots=slots,
                              max_seq_len=smax, page_size=ps,
                              num_pages=pages, prefill_chunk=chunk,
                              max_queue=2 * n + 2, quant=quant,
                              speculate_k=spec_k, draft_source=source)

    def reqs(sampled):
        out = []
        for i, w in enumerate(work):
            kw = {}
            if sampled and i % 3 == 1:
                kw = dict(do_sample=True, temperature=0.7 + 0.05 * (i % 4),
                          top_p=0.9, seed=11 + i)
            out.append(serving.Request(w["prompt"],
                                       max_new_tokens=w["max_new"], **kw))
        return out

    if deterministic:
        configs = (
            ("fp32+int8-draft", None, "quant"),
            ("fp32+shallow-draft", None, "shallow"),
            ("int8+self-draft", QuantSpec("int8", "int8"), "quant"),
        )
        rungs = []
        ok_parity = ok_freeze = True
        for name, quant, source in configs:
            base_reqs = reqs(sampled=True)
            base_res = build(0, None, quant).run(base_reqs)
            base = [base_res[r.request_id].tokens for r in base_reqs]
            eng = build(k, source, quant)
            profiler.reset_serving_counters()
            w1 = reqs(sampled=True)
            res1 = eng.run(w1)
            toks1 = [res1[r.request_id].tokens for r in w1]
            c1 = profiler.serving_counters()
            # second wave through the SAME engine: different residual page
            # state and admission interleaving, zero new traces allowed
            w2 = reqs(sampled=True)
            res2 = eng.run(w2)
            toks2 = [res2[r.request_id].tokens for r in w2]
            c2 = profiler.serving_counters()
            par = toks1 == base and toks2 == base
            frozen = all(c1[t] == c2[t] for t in
                         ("spec_draft_traces", "spec_verify_traces",
                          "paged_traces", "write_traces"))
            ok_parity = ok_parity and par
            ok_freeze = ok_freeze and frozen
            rungs.append({
                "config": name, "parity": par, "trace_frozen": frozen,
                "accept_rate": round(c2["accept_rate"], 3),
                "tokens_per_dispatch": round(c2["tokens_per_dispatch"], 2),
                "draft_traces": c2["spec_draft_traces"],
                "verify_traces": c2["spec_verify_traces"],
            })
        out = {"bench": "serving_spec_smoke", "requests": n,
               "backend": jax.default_backend(), "k": k,
               "deterministic": True, "parity": ok_parity,
               "trace_frozen": ok_freeze,
               # self-draft rungs only: a shallow draft of a random-init
               # model has no reason to agree with the full model
               "min_accept_rate": min(r["accept_rate"] for r in rungs
                                      if "shallow" not in r["config"]),
               "rungs": rungs}
        print(json.dumps(out))
        return out

    # -- timed: plain decode vs speculate_k=4 at equal engine config -------
    best = {}
    toks_by = {}
    for _ in range(max(1, repeats)):
        for name, spec_k in (("plain", 0), ("spec", k)):
            eng = build(spec_k, "quant" if spec_k else None)
            # warm every executable (prefill ladder + decode/draft/verify)
            # outside the clock
            warm = sorted({ps + 1, *eng._chunk_ladder})
            eng.generate([np.arange(1, ln + 1) for ln in warm],
                         max_new_tokens=2)
            eng.pool.clear_cache()
            _drive(eng, work[:4])
            profiler.reset_serving_counters()
            toks, wall, _stamps = _drive(eng, work)
            c = profiler.serving_counters()
            toks_by.setdefault(name, toks)
            assert toks_by[name] == toks, f"{name} nondeterministic"
            rec = {"tokens_per_s": round(sum(len(t) for t in toks) / wall, 1),
                   "wall_s": round(wall, 3)}
            if spec_k:
                rec["accept_rate"] = round(c["accept_rate"], 3)
                rec["tokens_per_dispatch"] = round(
                    c["tokens_per_dispatch"], 2)
            if name not in best or rec["wall_s"] < best[name]["wall_s"]:
                best[name] = rec
    out = {
        "bench": "serving_spec_smoke", "requests": n,
        "backend": jax.default_backend(), "k": k,
        "parity": toks_by["plain"] == toks_by["spec"],
        "plain": best["plain"], "spec": best["spec"],
        "speedup": round(best["spec"]["tokens_per_s"]
                         / max(best["plain"]["tokens_per_s"], 1e-9), 2),
    }
    print(json.dumps(out))
    return out


def run_adapter_rung(quick=True, deterministic=False, repeats=3):
    """Many-model serving (serving/adapters.py): N LoRA-class variants of
    one base checkpoint on ONE paged engine, vs the alternatives a fleet
    actually has. Two comparisons:

    * HBM ledger — serving N variants as resident low-rank deltas costs
      ``param_bytes + slab_bytes`` where full weight copies cost
      ``(N+1) * param_bytes``; reported via the registry's own
      ``row_bytes``/``slab_bytes`` accounting.
    * Throughput (timed mode) — mixed-tenant traffic on the adapter
      engine (every tenant in ONE continuous batch, adapter ids traced
      per slot) vs the swap-per-tenant baseline: the SAME engine without
      adapters, requests grouped by tenant, a full ``swap_params`` to
      that tenant's MERGED weights (W + A@B * alpha/r) between groups —
      the best case for the baseline (minimum swaps, FCFS within group).
      The baseline pays the swap uploads, the prefix-cache flush per
      swap, and one batch-drain tail per tenant; the adapter engine pays
      a delta GEMM epilogue. Gate: adapter engine >= 1.15x tokens/s.

    Deterministic mode (tier-1): parity — every request in a mixed
    greedy+sampled mixed-adapter batch is BITWISE its adapter's solo
    ``generate_from_params(adapters=...)`` stream — plus the frozen-
    executable gate (hot load/evict/swap between two waves, zero new
    paged traces) and the HBM ledger; no wall-clock gates."""
    from paddle_tpu import profiler
    params, cfg = _paged_model(deterministic)
    n_ad = 3 if deterministic else 6
    rank = 4 if deterministic else 8
    if deterministic:
        smax, ps, slots, chunk = 48, 8, 4, 8
        n, repeats = 10, 1
        short_new = (3, 7)
    else:
        smax, ps, slots, chunk = 256, 16, 8, 64
        n = 48 if quick else 96
        short_new = (8, 25)
    pages = slots * smax // ps + 1
    rng = np.random.default_rng(0)
    H = cfg.hidden_size
    dims = {"out_w": (H, H), "up_w": (H, 4 * H), "down_w": (4 * H, H)}
    alphas = {a: 2.0 * rank for a in range(1, n_ad + 1)}
    deltas = {
        a: {t: (rng.standard_normal(
                    (cfg.num_layers, dims[t][0], rank)).astype(np.float32)
                * 0.05,
                rng.standard_normal(
                    (cfg.num_layers, rank, dims[t][1])).astype(np.float32)
                * 0.05)
            for t in dims}
        for a in range(1, n_ad + 1)}

    def build(adapters=True):
        kw = dict(params=params, config=cfg, num_slots=slots,
                  max_seq_len=smax, page_size=ps, num_pages=pages,
                  prefill_chunk=chunk, max_queue=2 * n + 2)
        if adapters:
            kw.update(adapter_slots=n_ad, adapter_rank=rank)
        eng = serving.Engine(**kw)
        if adapters:
            for a in range(1, n_ad + 1):
                eng.load_adapter(a, deltas[a], alpha=alphas[a])
        return eng

    def reqs(shift=0, sampled=deterministic):
        out = []
        for i in range(n):
            plen = int(rng.integers(4, smax // 4))
            kw = {"adapter": (i + shift) % (n_ad + 1)}
            if sampled and i % 3 == 1:
                kw.update(do_sample=True, temperature=0.8, top_p=0.9,
                          seed=31 + i)
            out.append(serving.Request(
                rng.integers(0, cfg.vocab_size, plen),
                max_new_tokens=int(rng.integers(*short_new)), **kw))
        return out

    param_bytes = sum(int(np.prod(x.shape)) * x.dtype.itemsize
                      for x in jax.tree_util.tree_leaves(params))
    eng = build()
    hbm = {
        "param_bytes": param_bytes,
        "adapter_row_bytes": eng.adapters.row_bytes(),
        "adapter_slab_bytes": eng.adapters.slab_bytes(),
        "adapter_engine_bytes": param_bytes + eng.adapters.slab_bytes(),
        "full_copy_fleet_bytes": (n_ad + 1) * param_bytes,
    }
    hbm["ratio"] = round(hbm["adapter_engine_bytes"]
                         / hbm["full_copy_fleet_bytes"], 4)

    if deterministic:
        profiler.reset_serving_counters()
        w1 = reqs()
        res1 = eng.run(w1)
        slabs = eng.adapters.device_slabs()
        parity = True
        for r in w1:
            kw = {}
            if r.do_sample:
                kw = dict(do_sample=True, temperature=r.temperature,
                          top_p=r.top_p, seed=r.seed)
            ref = generate_from_params(
                params, np.asarray(r.prompt)[None], cfg,
                max_new_tokens=r.max_new_tokens,
                adapters=(r.adapter or 0, slabs), **kw)
            got = res1[r.request_id].tokens
            ref = np.asarray(ref._data)[0, len(r.prompt):].tolist()
            parity = parity and got == ref[:len(got)]
        c1 = profiler.serving_counters()
        # hot ops between waves: content-only rewrites, zero new traces
        eng.swap_adapter(1, deltas[2], alpha=alphas[2])
        eng.evict_adapter(3)
        eng.load_adapter(3, deltas[1], alpha=alphas[1])
        eng.run(reqs(shift=1))
        c2 = profiler.serving_counters()
        frozen = c1["paged_traces"] == c2["paged_traces"]
        out = {"bench": "serving_adapter_smoke", "requests": 2 * n,
               "backend": jax.default_backend(), "deterministic": True,
               "adapters": n_ad, "rank": rank, "parity": parity,
               "trace_frozen": frozen,
               "paged_traces": c2["paged_traces"],
               "adapter_ops": {"loads": c2["adapter_loads"],
                               "evicts": c2["adapter_evicts"],
                               "swaps": c2["adapter_swaps"]},
               "hbm": hbm}
        print(json.dumps(out))
        return out

    # -- timed: one mixed-tenant batch vs swap-per-tenant ------------------
    def merged_params(a):
        blocks = dict(params["blocks"])
        for t, (A, B) in deltas[a].items():
            scale = alphas[a] / rank
            blocks[t] = np.asarray(blocks[t]) + scale * np.einsum(
                "lkr,lrf->lkf", A, B)
        return {**params, "blocks": blocks}

    merged = {a: merged_params(a) for a in range(1, n_ad + 1)}
    work = reqs(sampled=False)
    by_tenant = {}
    for r in work:
        by_tenant.setdefault(r.adapter, []).append(r)

    def clone(r, adapter=True):
        return serving.Request(r.prompt, max_new_tokens=r.max_new_tokens,
                               adapter=r.adapter if adapter else None)

    best = {}
    for _ in range(max(1, repeats)):
        # adapter engine: every tenant shares one continuous batch
        eng = build()
        eng.generate([np.arange(1, ln + 1)
                      for ln in sorted({ps + 1, *eng._chunk_ladder})],
                     max_new_tokens=2)
        batch = [clone(r) for r in work]
        t0 = time.perf_counter()
        res = eng.run(batch)
        wall = time.perf_counter() - t0
        tok = sum(len(v.tokens) for v in res.values())
        rec = {"tokens": tok, "wall_s": round(wall, 3),
               "tokens_per_s": round(tok / wall, 1)}
        if "adapter" not in best or rec["wall_s"] < best["adapter"]["wall_s"]:
            best["adapter"] = rec

        # swap baseline: per-tenant groups on an adapter-less engine,
        # swap_params to the tenant's merged weights between groups
        eng = build(adapters=False)
        eng.generate([np.arange(1, ln + 1)
                      for ln in sorted({ps + 1, *eng._chunk_ladder})],
                     max_new_tokens=2)
        t0 = time.perf_counter()
        tok = 0
        for a in sorted(by_tenant):
            if a != 0:
                eng.swap_params(merged[a])
            res = eng.run([clone(r, adapter=False) for r in by_tenant[a]])
            tok += sum(len(v.tokens) for v in res.values())
        wall = time.perf_counter() - t0
        eng.swap_params(params)       # leave the engine on base weights
        rec = {"tokens": tok, "wall_s": round(wall, 3),
               "tokens_per_s": round(tok / wall, 1),
               "weight_swaps": len(by_tenant) - 1}
        if "swap" not in best or rec["wall_s"] < best["swap"]["wall_s"]:
            best["swap"] = rec

    out = {"bench": "serving_adapter_smoke", "requests": n,
           "backend": jax.default_backend(), "adapters": n_ad,
           "rank": rank, "hbm": hbm,
           "adapter_engine": best["adapter"], "swap_baseline": best["swap"]}
    out["speedup"] = round(best["adapter"]["tokens_per_s"]
                           / max(best["swap"]["tokens_per_s"], 1e-9), 2)
    print(json.dumps(out))
    return out


def _drive_sup(sup, work, seed0=0):
    """Drive a supervisor fleet over backlogged ``work``; returns
    (token lists in workload order, wall seconds, emission stamps)."""
    stamps = {}

    def cb(r, t):
        stamps.setdefault(r.request_id, []).append(time.perf_counter())

    reqs = [serving.Request(w["prompt"], max_new_tokens=w["max_new"],
                            on_token=cb, seed=seed0 + i)
            for i, w in enumerate(work)]
    t0 = time.perf_counter()
    results = sup.run(reqs)
    wall = time.perf_counter() - t0
    tokens = [results[r.request_id].tokens for r in reqs]
    return tokens, wall, [stamps.get(r.request_id, []) for r in reqs]


def run_disagg_rung(quick=True, deterministic=False, rate=None, repeats=3):
    """Disaggregated prefill/decode serving (serving/kv_transfer.py):
    a 1-prefill + 1-decode fleet vs the same two engines colocated
    ("both"/"both") under mixed traffic. The prefill worker runs only
    big-chunk rungs and streams finished KV pages to the decode worker
    (bounded installs per decode boundary), so long prefills never stall
    the decode batch; repeat traffic whose prefix the decode worker
    already caches routes straight there — no prefill, no transfer.

    Reported: backlogged tokens/s and short-request inter-token p99 for
    both fleets, transfer pages/bytes by KV dtype, prefill handoffs,
    affinity hits + hit rate on the repeat wave, drops. Parity gate:
    the disaggregated streams are BITWISE the single engine's, fp32 and
    int8. Deterministic mode drops the wall-clock gates (tier-1).

    The timed GATE is decode-boundary p99: the p99 duration of the
    engine boundaries a user's next token actually waits behind. On the
    colocated fleet those boundaries carry whole prefill chunk rungs (an
    XL chunk stalls every decoding slot on that replica); the disagg
    decode worker's boundaries carry only the [B,1] decode dispatch plus
    the BOUNDED per-boundary page installs, so its p99 collapses. This
    single-process driver steps replicas serially, so fleet WALL time
    adds the prefill worker's compute to every round — wall tokens/s
    and inter-token p99 are reported for the record, but the boundary
    distribution is the number that survives the move to parallel
    chips (each worker stepping on its own)."""
    from paddle_tpu import profiler
    params, cfg = _paged_model(deterministic)
    if deterministic:
        smax, ps, slots = 48, 8, 3
        short_pl, long_pl, xl_pl = (3, 15), (20, 33), (34, 41)
        short_new, long_new, xl_new = (3, 7), (4, 9), (4, 8)
        n, chunk, repeats = 8, ps, 1
    else:
        smax, ps, slots = 512, 16, 8
        short_pl, long_pl, xl_pl = (18, 49), (96, 129), (320, 441)
        short_new, long_new, xl_new = (24, 49), (40, 64), (16, 33)
        n, chunk = (48 if quick else 96), 4 * ps
    pages = slots * smax // ps + 1
    work = _mixed_workload(n, rate, np.random.default_rng(0), short_pl,
                           long_pl, xl_pl, short_new, long_new, xl_new,
                           cfg.vocab_size, sys_len=2 * ps, tmpl_len=0)

    def build(quant=None):
        return serving.Engine(params=params, config=cfg, num_slots=slots,
                              max_seq_len=smax, page_size=ps,
                              num_pages=pages, prefill_chunk=chunk,
                              max_queue=2 * n + 2, quant=quant)

    # -- parity + transfer ledger per dtype (untimed) ----------------------
    parity = True
    transfer_dtype = {}
    ledger = {}
    for quant in (None, "int8"):
        base_reqs = [serving.Request(w["prompt"],
                                     max_new_tokens=w["max_new"], seed=i)
                     for i, w in enumerate(work)]
        base_res = build(quant).run(base_reqs)
        base = [base_res[r.request_id].tokens for r in base_reqs]
        profiler.reset_serving_counters()
        sup = serving.ServingSupervisor(lambda: build(quant),
                                        num_replicas=2,
                                        roles=("prefill", "decode"))
        toks1, _w, _s = _drive_sup(sup, work)
        parity = parity and toks1 == base
        # repeat wave: shared prefixes now live in the decode worker's
        # cache -> affinity routing skips prefill AND transfer
        toks2, _w, _s = _drive_sup(sup, work)
        parity = parity and toks2 == base
        sup.shutdown()
        c = profiler.serving_counters()
        dtype = str(np.dtype(cfg.compute_dtype or "float32")
                    if quant is None else quant)
        transfer_dtype[dtype] = c["transfer_bytes"]
        if quant is None:
            ledger = {
                "prefill_handoffs": c["prefill_handoffs"],
                "transfers": c["transfers"],
                "transfer_pages": c["transfer_pages"],
                "transfer_bytes": c["transfer_bytes"],
                "transfer_installs": c["transfer_installs"],
                "affinity_hits": c["affinity_hits"],
                "affinity_hit_rate": round(c["affinity_hits"] / n, 3),
                "disagg_fallbacks": c["disagg_fallbacks"],
                "dropped": c["dropped"],
            }

    out = {
        "bench": "serving_disagg_smoke", "requests": n,
        "backend": jax.default_backend(), "page_size": ps,
        "parity": parity, "transfer_dtype": transfer_dtype, **ledger,
    }

    # -- timed fleets: disagg vs colocated at equal chip count -------------
    if not deterministic:
        def instrument(sup, idxs):
            """Record step durations of the replicas in ``idxs`` — the
            boundaries a decoding user's next token waits behind."""
            times = []
            for i in idxs:
                eng = sup._replicas[i].engine
                orig = eng.step

                def timed(orig=orig):
                    t0 = time.perf_counter()
                    busy = orig()
                    times.append(time.perf_counter() - t0)
                    return busy
                eng.step = timed
            return times

        # the per-boundary install budget is THE knob bounding what a
        # decode boundary pays for transfers: on a backend without
        # buffer donation (CPU) each page write costs a full pool copy,
        # so the rung runs the budget at 1 there — on TPU the donated
        # in-place write keeps the default of 4 cheap
        from paddle_tpu.flags import get_flags
        budget = 1 if jax.default_backend() == "cpu" else \
            get_flags().get("FLAGS_serving_transfer_pages_per_boundary", 4)
        prev = get_flags().get("FLAGS_serving_transfer_pages_per_boundary", 4)
        paddle_tpu.set_flags(
            {"FLAGS_serving_transfer_pages_per_boundary": budget})
        out["transfer_pages_per_boundary"] = budget
        best = {}
        try:
            for name, roles, token_idxs in (
                    ("colocated", None, (0, 1)),
                    ("disagg", ("prefill", "decode"), (1,))):
                for _ in range(max(1, repeats)):
                    kw = {} if roles is None else {"roles": roles}
                    sup = serving.ServingSupervisor(lambda: build(),
                                                    num_replicas=2, **kw)
                    profiler.reset_serving_counters()
                    boundaries = instrument(sup, token_idxs)
                    toks, wall, stamps = _drive_sup(sup, work)
                    sup.shutdown()
                    rec = {
                        "tokens_per_s": round(
                            sum(len(t) for t in toks) / wall, 1),
                        "wall_s": round(wall, 3),
                        "inter_token_p99": round(
                            _intertoken_p99(stamps, work), 4),
                        "decode_boundary_p99": round(float(
                            np.percentile(boundaries, 99)), 4),
                    }
                    if name not in best \
                            or rec["wall_s"] < best[name]["wall_s"]:
                        best[name] = rec
        finally:
            paddle_tpu.set_flags(
                {"FLAGS_serving_transfer_pages_per_boundary": prev})
        out.update(best)
    print(json.dumps(out))
    return out


def run_ladder(quick=True):
    params, cfg = _model(quick)
    n = 24 if quick else 48
    rates = [1e9] if quick else [2.0, 8.0, 1e9]   # req/s; 1e9 = backlogged
    out = []
    for rate in rates:
        work = _workload(n, rate, np.random.default_rng(0))
        static = run_static(params, cfg, work)
        cont = run_continuous(params, cfg, work)
        rung = {
            "bench": "serving_smoke", "requests": n,
            "rate_req_s": None if rate > 1e6 else rate,
            "backend": jax.default_backend(),
            "static": static, "continuous": cont,
            "speedup": round(cont["tokens_per_s"] / static["tokens_per_s"], 2),
            "ttft_p50_ratio": round(
                static["ttft_p50_s"] / max(cont["ttft_p50_s"], 1e-9), 1),
        }
        print(json.dumps(rung))
        out.append(rung)
    return out


if __name__ == "__main__":
    if "--mp" in sys.argv or "--mp-det" in sys.argv:
        # tensor-parallel ladder: memory-equal single-chip vs mp in {2,4}
        det = "--mp-det" in sys.argv
        backends = ("gspmd", "ring", "fused") if det else ("gspmd", "ring")
        out = run_mp_rung(deterministic=det, backends=backends)
        ok_bw = out["outputs_match"]
        sp = out.get("best_speedup")
        if det:
            # the deterministic model is parity/dispatch-count rig only —
            # it is far too small to amortize collective overhead
            print(f"# tensor-parallel serving (deterministic): outputs "
                  f"bitwise across all rungs incl. fused: "
                  f"{'PASS' if ok_bw else 'FAIL'}")
        else:
            ok_tp = sp is not None and sp >= 1.4
            print(f"# tensor-parallel serving (memory-equal per chip): "
                  f"best mp speedup "
                  f"{'n/a' if sp is None else f'{sp:.2f}x'} tokens/s "
                  f"({'PASS' if ok_tp else 'FAIL'} >= 1.4x gate), "
                  f"outputs bitwise across all rungs: "
                  f"{'PASS' if ok_bw else 'FAIL'}")
        sys.exit(0)
    if "--disagg" in sys.argv or "--disagg-det" in sys.argv:
        # disaggregated prefill/decode vs colocated at equal chip count
        quick = "--full" not in sys.argv
        det = "--disagg-det" in sys.argv
        out = run_disagg_rung(quick=quick, deterministic=det)
        ok_par = out["parity"]
        ok_drop = out["dropped"] == 0
        gate = ""
        if "disagg" in out:
            ok_p99 = (out["disagg"]["decode_boundary_p99"]
                      <= out["colocated"]["decode_boundary_p99"])
            gate = (f", decode-boundary p99 "
                    f"{out['colocated']['decode_boundary_p99'] * 1e3:.1f}ms "
                    f"-> {out['disagg']['decode_boundary_p99'] * 1e3:.1f}ms "
                    f"({'PASS' if ok_p99 else 'FAIL'} prefill off the "
                    f"decode path), wall tokens/s "
                    f"{out['colocated']['tokens_per_s']} -> "
                    f"{out['disagg']['tokens_per_s']} (serialized driver)")
        print(f"# disaggregated serving (1 prefill + 1 decode): bitwise "
              f"parity fp32+int8: {'PASS' if ok_par else 'FAIL'}, "
              f"handoffs {out['prefill_handoffs']}, transfer bytes "
              f"{out['transfer_dtype']}, affinity hit rate "
              f"{out['affinity_hit_rate'] * 100:.0f}% on the repeat wave, "
              f"dropped {out['dropped']} "
              f"({'PASS' if ok_drop else 'FAIL'} zero){gate}")
        sys.exit(0)
    if "--spec" in sys.argv or "--spec-det" in sys.argv:
        # speculative k-token decode vs plain one-token decode
        quick = "--full" not in sys.argv
        det = "--spec-det" in sys.argv
        out = run_spec_rung(quick=quick, deterministic=det)
        if det:
            ok = out["parity"] and out["trace_frozen"] \
                and out["min_accept_rate"] > 0.2
            print(f"# speculative serving (deterministic, k={out['k']}): "
                  f"greedy+sampled streams bitwise the plain engine's "
                  f"across dtype configs: "
                  f"{'PASS' if out['parity'] else 'FAIL'}, draft/verify "
                  f"executables frozen under churn: "
                  f"{'PASS' if out['trace_frozen'] else 'FAIL'}, "
                  f"self-draft accept rate {out['min_accept_rate'] * 100:.0f}"
                  f"% ({'PASS' if ok else 'FAIL'} overall)")
        else:
            ok_sp = out["speedup"] >= 1.3
            ok_tpd = out["spec"]["tokens_per_dispatch"] > 1.5
            print(f"# speculative serving (backlogged, k={out['k']}): "
                  f"{out['speedup']:.2f}x tokens/s "
                  f"({'PASS' if ok_sp else 'FAIL'} >= 1.3x gate), "
                  f"tokens/dispatch {out['spec']['tokens_per_dispatch']:.2f} "
                  f"({'PASS' if ok_tpd else 'FAIL'} > 1.5), accept rate "
                  f"{out['spec']['accept_rate'] * 100:.0f}%, streams bitwise "
                  f"the plain engine's: "
                  f"{'PASS' if out['parity'] else 'FAIL'}")
        sys.exit(0)
    if "--adapters" in sys.argv or "--adapters-det" in sys.argv:
        # many-model serving: N LoRA-class adapters on one paged engine
        quick = "--full" not in sys.argv
        det = "--adapters-det" in sys.argv
        out = run_adapter_rung(quick=quick, deterministic=det)
        ratio = out["hbm"]["ratio"]
        ok_hbm = ratio < 0.5
        if det:
            ok = out["parity"] and out["trace_frozen"]
            print(f"# many-model serving (deterministic, "
                  f"{out['adapters']} adapters r{out['rank']}): mixed-"
                  f"adapter batch bitwise vs solo per-adapter reference: "
                  f"{'PASS' if out['parity'] else 'FAIL'}, executables "
                  f"frozen across hot load/evict/swap "
                  f"(paged_traces={out['paged_traces']}): "
                  f"{'PASS' if out['trace_frozen'] else 'FAIL'}, HBM "
                  f"{ratio:.3f}x of full-copy fleet "
                  f"({'PASS' if ok_hbm else 'FAIL'} < 0.5) "
                  f"({'PASS' if ok and ok_hbm else 'FAIL'} overall)")
        else:
            ok_sp = out["speedup"] >= 1.15
            print(f"# many-model serving ({out['adapters']} adapters "
                  f"r{out['rank']} on one engine vs swap-per-tenant): "
                  f"{out['speedup']:.2f}x tokens/s "
                  f"({'PASS' if ok_sp else 'FAIL'} >= 1.15x gate), HBM "
                  f"{out['hbm']['adapter_engine_bytes']} vs "
                  f"{out['hbm']['full_copy_fleet_bytes']} bytes for "
                  f"{out['adapters'] + 1} variants = {ratio:.3f}x "
                  f"({'PASS' if ok_hbm else 'FAIL'} < 0.5)")
        sys.exit(0)
    if "--quant" in sys.argv:
        # quantized vs fp at equal KV memory: int8 weights + int8 KV
        quick = "--full" not in sys.argv
        out = run_quant_rung(quick=quick)
        ratio = out["capacity_throughput_ratio"]
        ok_cap = ratio > 1.0
        ok_drift = out["max_logit_drift"] < 0.15 * max(
            out["max_abs_logit"], 1.0)
        print(f"# quantized serving (equal KV memory, int8 w + int8 kv): "
              f"slots x tokens/s {ratio:.2f}x "
              f"({'PASS' if ok_cap else 'FAIL'} > 1.0 gate), "
              f"pages {out['fp']['pages']} -> {out['quant']['pages']}, "
              f"kv bytes/tok {out['fp']['kv_bytes_per_token']} -> "
              f"{out['quant']['kv_bytes_per_token']}, max logit drift "
              f"{out['max_logit_drift']:.2e} "
              f"({'PASS' if ok_drift else 'FAIL'} bounded), greedy "
              f"agreement {out['greedy_agreement'] * 100:.1f}%, "
              f"over-budget context served only quantized: "
              f"{out['capacity_only_quant']}")
        sys.exit(0)
    if "--paged" in sys.argv:
        # paged vs pooled ladder: backlogged + (full) a Poisson-arrival rung
        quick = "--full" not in sys.argv
        rungs = [run_paged_rung(quick=quick)]
        if not quick:
            rungs.append(run_paged_rung(quick=False, rate=8.0))
        cap = rungs[0]
        ok_tp = cap["speedup"] >= 1.3
        ok_it = (cap["paged"]["intertoken_p99_s"]
                 <= cap["pooled"]["intertoken_p99_s"])
        ok_waste = cap["paged"]["prefill_waste_max"] < cap["page_size"]
        print(f"# paged vs pooled (equal KV memory, mixed lengths, "
              f"backlogged): {cap['speedup']:.2f}x tokens/s "
              f"({'PASS' if ok_tp else 'FAIL'} >= 1.3x gate), "
              f"inter-token p99 {cap['paged']['intertoken_p99_s'] * 1e3:.1f}"
              f"ms vs {cap['pooled']['intertoken_p99_s'] * 1e3:.1f}ms "
              f"({'PASS' if ok_it else 'FAIL'} not regressed), "
              f"chunked prefill waste max "
              f"{cap['paged']['prefill_waste_max']} tok "
              f"({'PASS' if ok_waste else 'FAIL'} < page_size "
              f"{cap['page_size']}), over-Smax request served from pages: "
              f"{cap['capacity_only_paged']}")
        sys.exit(0)
    results = run_ladder(quick="--full" not in sys.argv)
    # tokens/s gates the CAPACITY-bound (backlogged) rungs; in the
    # arrival-limited rungs both systems idle between requests and the
    # meaningful win is TTFT (tokens stream per iteration instead of at
    # whole-batch completion)
    cap = min(r["speedup"] for r in results if r["rate_req_s"] is None)
    ttft = max(r["ttft_p50_ratio"] for r in results)
    print(f"# continuous batching vs static whole-batch: backlogged "
          f"speedup {cap:.2f}x "
          f"({'PASS' if cap >= 1.5 else 'FAIL'} >= 1.5x gate), "
          f"best p50-TTFT ratio {ttft:.1f}x")
