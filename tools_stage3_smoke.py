#!/usr/bin/env python
"""Stage-3 full-offload smoke on the real chip: 6.7B (and 13B stretch)
GPT training on a single 16 GB chip backed by host RAM.

  python tools_stage3_smoke.py 6.7B [stream|host]
  python tools_stage3_smoke.py 13B  [stream|host]

Append results to TPU_SMOKE.log.
"""
import sys
import time

sys.path.insert(0, "/root/repo")


def main():
    model = sys.argv[1] if len(sys.argv) > 1 else "6.7B"
    update = sys.argv[2] if len(sys.argv) > 2 else "stream"
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT_CONFIGS
    from paddle_tpu.models.gpt_stage3_offload import Stage3OffloadTrainStep
    from bench import model_flops_per_token, peak_flops_bf16

    assert jax.default_backend() == "tpu", jax.devices()
    if model == "tiny":
        # cheap probe for the offload machinery (esp. the compute_on
        # host-update branch) before burning time on a 6.7B attempt
        from paddle_tpu.models.gpt import GPTConfig
        GPT_CONFIGS["gpt3-tiny"] = GPTConfig(
            vocab_size=1024, hidden_size=256, num_layers=4, num_heads=4,
            max_seq_len=256)
    name = f"gpt3-{model}"
    cfg = GPT_CONFIGS[name]
    batch, seq = (1, 2048) if model == "13B" else \
        (2, 256) if model == "tiny" else (2, 2048)
    cfg.max_seq_len = max(cfg.max_seq_len, seq)
    cfg.use_flash = True
    cfg.compute_dtype = "bfloat16"
    opt = paddle.optimizer.AdamW(1e-4, moment_dtype="bfloat16")
    t0 = time.time()
    print(f"{name} bs={batch} seq={seq} update={update}: init "
          f"(host-resident params)...", flush=True)
    step = Stage3OffloadTrainStep(cfg, opt, param_dtype=jnp.bfloat16,
                                  update=update)
    n = step.num_params()
    print(f"  {n/1e9:.2f}B params resident on host "
          f"(+{time.time()-t0:.0f}s)", flush=True)
    ids = jax.random.randint(jax.random.key(0), (batch, seq), 0,
                             cfg.vocab_size, jnp.int32)
    loss = step(ids)
    print(f"  compile+step0 done loss={float(jax.device_get(loss)):.4f} "
          f"(+{time.time()-t0:.0f}s)", flush=True)
    steps = 3
    t1 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids)
    jax.device_get(loss)
    dt = (time.perf_counter() - t1) / steps
    tok_s = batch * seq / dt
    fpt, _ = model_flops_per_token(cfg, seq)
    peak = peak_flops_bf16(getattr(jax.devices()[0], "device_kind", ""))
    print(f"STAGE3 {name} bs={batch} seq={seq} update={update}: "
          f"{tok_s:.1f} tok/s, {dt:.2f} s/step, "
          f"MFU {tok_s*fpt/peak*100:.1f}%, "
          f"loss {float(jax.device_get(loss)):.4f}", flush=True)


if __name__ == "__main__":
    main()
