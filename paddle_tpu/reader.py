"""Legacy reader decorators (ref: python/paddle/reader/decorator.py).

Plain generator combinators with no device component; kept for API parity
with older Paddle training scripts (`paddle.batch` lives in
framework/extras.py). paddle.io.DataLoader is the modern path.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading


def cache(reader):
    """Cache all samples in memory on first epoch (ref decorator.py:45).
    A partial first epoch (source raised) is discarded, not kept."""
    all_data = []
    filled = []

    def cached():
        if not filled:
            fresh = list(reader())  # completes or raises — never partial
            all_data.extend(fresh)
            filled.append(True)
        yield from all_data
    return cached


def map_readers(func, *readers):
    """Yield func(*samples) across readers zipped (ref decorator.py:84)."""
    def reader():
        yield from map(func, *[r() for r in readers])
    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (ref decorator.py:125)."""
    def shuffled():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    """Concatenate readers (ref decorator.py:174)."""
    def chained():
        for r in readers:
            yield from r()
    return chained


def compose(*readers, **kwargs):
    """Zip readers into tuple samples (ref decorator.py:238)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        its = [r() for r in readers]
        for samples in itertools.zip_longest(*its):
            if check_alignment and any(s is None for s in samples):
                raise ValueError("readers have different lengths")
            yield sum((make_tuple(s) for s in samples), ())
    return composed


def buffered(reader, size):
    """Background-thread prefetch buffer (ref decorator.py:296)."""
    end = object()

    def buffered_reader():
        q = queue.Queue(maxsize=size)
        err = []

        def worker():
            try:
                for s in reader():
                    q.put(s)
            except BaseException as e:  # noqa: BLE001 — re-raised in consumer
                err.append(e)
            finally:
                q.put(end)  # ALWAYS unblock the consumer

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                if err:
                    raise err[0]
                return
            yield s
    return buffered_reader


def firstn(reader, n):
    """First n samples (ref decorator.py:358)."""
    def firstn_reader():
        yield from itertools.islice(reader(), n)
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool map over a reader (ref decorator.py:403 — processes in
    the reference; threads suffice here because mappers are numpy/jax-bound,
    not GIL-bound python loops)."""
    from concurrent.futures import ThreadPoolExecutor

    def mapped():
        with ThreadPoolExecutor(max_workers=process_num) as pool:
            it = reader()
            pending = []
            for s in it:
                pending.append(pool.submit(mapper, s))
                if len(pending) >= buffer_size:
                    yield pending.pop(0).result()
            for f in pending:
                yield f.result()
    return mapped
