"""Framework flags (ref: FLAGS_* in paddle/fluid/framework + paddle.set_flags).

TPU-relevant knobs only; unknown flags are stored and returned verbatim so
scripts written against the reference don't crash.
"""
from __future__ import annotations

_FLAGS = {
    "FLAGS_use_flash_attention": True,
    "FLAGS_cudnn_deterministic": False,   # accepted, no-op on TPU
    "FLAGS_embedding_deterministic": False,
    "FLAGS_use_remat": False,
    "FLAGS_matmul_precision": "default",  # default|highest (f32 on MXU)
    "FLAGS_donate_buffers": True,
    # Eager dispatch cache: route repeat op dispatches through cached
    # jax.jit executables (dispatch.py). Disable to force op-by-op eager
    # execution when debugging numerics or tracing issues.
    "FLAGS_eager_jit_cache": True,
    # Persist XLA executables across processes (JAX_COMPILATION_CACHE_DIR,
    # default <cwd>/.jax_cache — see framework/compilation_cache.py).
    "FLAGS_persistent_compilation_cache": True,
    # -- explicit gradient communication (distributed/grad_comm.py) ---------
    # Master switch: "auto" activates the explicit schedule only when one of
    # the two knobs below asks for a non-default schedule; True/"on" forces
    # it (gives the allreduce-fp32 baseline its own comm counters); False
    # disables it entirely. Default path is byte-identical to the seed.
    "FLAGS_grad_comm": "auto",
    # Weight-update sharding (ZeRO-1 per arXiv:2004.13336): reduce-scatter
    # grads, fused optimizer update on each replica's 1/n flat shard (slots
    # stored sharded), all-gather updated params — halves grad-reduce wire
    # bytes vs all-reduce and divides update FLOPs/slot HBM by the dp size.
    "FLAGS_weight_update_sharding": False,
    # Wire dtype for the gradient reduction: float32 | bfloat16 | int8.
    # Compressed dtypes move over an all_to_all exchange and accumulate in
    # fp32 on the receiver (EQuARX-style per-2048-chunk scales for int8);
    # master/update math stays fp32.
    "FLAGS_allreduce_dtype": "float32",
    # Flat-buffer bucket size for grad collectives: few, large transfers.
    "FLAGS_grad_bucket_bytes": 16 * 2 ** 20,
    # -- tensor-parallel schedule (distributed/tp_overlap.py) ---------------
    # Sequence parallelism (Megatron-SP done the shard_map way): norms/
    # residuals between TP blocks compute on seq-sharded activations; the
    # two per-block all-reduces become a reduce-scatter after RowParallel
    # and an all-gather before ColumnParallel — same wire bytes, 1/mp
    # activation memory between blocks. Default OFF: the GSPMD schedule is
    # untouched and the compiled program is byte-identical to the seed.
    "FLAGS_sequence_parallel": False,
    # -- fault-tolerant runtime (jit/train_step.py anomaly guard) -----------
    # Compiled anomaly guard policy. "off" (default): the compiled step is
    # byte-identical to the unguarded program. "skip": an all-finite check
    # of loss+grads is fused into the step executable (shard-space psum'd
    # under grad_comm) and a bad step's update is skipped via lax.cond —
    # the step_ok flag rides back with the loss in ONE host fetch, no extra
    # sync. "rollback": skip, plus after FLAGS_anomaly_max_bad_steps
    # consecutive bad steps the attached CheckpointManager's latest
    # checkpoint is restored and the RNG stream fast-forwarded past the
    # poison batches.
    "FLAGS_anomaly_policy": "off",
    # Consecutive bad steps tolerated under "rollback" before restoring.
    "FLAGS_anomaly_max_bad_steps": 3,
    # -- continuous-batching serving engine (serving/engine.py) -------------
    # Decode-batch slot count B: the fixed batch dim of the pooled KV cache
    # and the one-token decode executable. More slots = more requests decoded
    # per iteration (throughput) at B x Smax x L x H KV memory.
    "FLAGS_serving_slots": 8,
    # KV pool sequence capacity Smax per slot; 0 = the model's max_seq_len.
    # Every request needs prompt_len + max_new_tokens <= Smax.
    "FLAGS_serving_max_seq_len": 0,
    # Prefill length buckets: a prompt is right-padded to the smallest
    # bucket that holds it, so steady state compiles ONE prefill executable
    # per bucket instead of one per prompt length. Buckets above Smax clamp.
    "FLAGS_serving_prefill_buckets": (64, 256, 1024),
    # Wait-queue bound: submit() past this raises QueueFullError — the
    # backpressure signal a frontend turns into HTTP 429 / retry-after.
    "FLAGS_serving_max_queue": 256,
    # KV-cache layout: "paged" (block-paged pool [L,P,page,nh,d] + slot->page
    # table, vLLM-style — admission is bounded by PAGES, not worst-case
    # Smax slots, long prompts prefill in chunks interleaved with decode,
    # and common prompt prefixes share physical pages copy-on-write) or
    # "pooled" (the PR 5 contiguous [L,B,Smax,nh,d] layout, kept as the
    # bitwise parity baseline).
    "FLAGS_serving_kv_layout": "paged",
    # Tokens per KV page. Smaller pages = less per-request fragmentation
    # (waste < page_size tokens per sequence) but a bigger page table.
    "FLAGS_serving_page_size": 16,
    # Physical pages in the paged pool. 0 = auto: num_slots * ceil(Smax /
    # page_size) + 1 (memory-equal to the pooled layout, +1 trash page).
    "FLAGS_serving_num_pages": 0,
    # Chunked-prefill budget: long prompts prefill in chunks interleaved
    # between decode iterations (Sarathi-style), so admitting a 1024-token
    # prompt costs each inter-token gap one chunk instead of a monolithic
    # prefill stall. Chunks walk a power-of-two LADDER of sizes (page_size
    # .. this value): bulk prefill rides the largest rung, the tail steps
    # down so per-request padding waste stays < page_size. Executable set
    # = the fused step at [B, 1] (decode) + one [1, rung] trace per ladder
    # rung actually used. Must be >= page_size.
    "FLAGS_serving_prefill_chunk": 16,
    # Hash-match admitted prompts against previously served ones and map
    # the common page-aligned prefix (or the exact full prompt) to the SAME
    # physical pages, copy-on-write on first divergence. Sharing is bitwise
    # safe: KV for a token depends only on the token prefix.
    "FLAGS_serving_prefix_cache": True,
    # Route the paged decode attention through the Pallas TPU kernel
    # (serving/paged_attention.py) instead of the pure-jnp page gather.
    # TPU-only; the kernel's online-softmax accumulation is numerically
    # equivalent but NOT bitwise identical to the jnp path — disable when
    # auditing bitwise parity on TPU.
    "FLAGS_serving_paged_kernel": True,
    # Tensor-parallel serving degree: > 1 builds the engine over a 1-D
    # 'mp' mesh of that many chips — GPT weights column-sharded (head-
    # major qkv), the paged KV pool sharded over its HEAD axis (per-chip
    # KV bytes ~ 1/mp; the host page table stays global), logits/embedding
    # vocab- and feature-sharded. The schedule is GATHER-ONLY, so engine
    # output stays BITWISE identical to the single-chip engine. The
    # collective rung comes from FLAGS_comm_backend ("mp=gspmd|ring|
    # fused"); an explicit Engine(mesh=/mp=/comm_backend=) overrides both
    # flags. 0/1 = single chip.
    "FLAGS_serving_mp": 0,
    # -- quantized serving (serving/quant.py + ops/pallas_kernels/
    # quant_gemm.py) -------------------------------------------------------
    # Weight storage dtype of the serving engine: "bf16" (= today's
    # full-precision bitwise-exact path, untouched), "int8" or "fp8"
    # (weight-only quantization: per-output-channel scales computed at
    # engine build or imported from a PTQ calibration via
    # Engine(quant=QuantSpec), dequant fused into the GEMM epilogue — on
    # the mp rungs the int8/fp8 shard feeds fused_gemm_ag directly, no fp
    # weight copy anywhere). The exactness contract becomes "exact at a
    # given dtype config": order-invariant, kill-and-resume bitwise, and
    # mp output bitwise identical to single-chip QUANTIZED output.
    "FLAGS_serving_weight_dtype": "bf16",
    # KV-pool storage dtype: "bf16" (full precision) | "int8" | "fp8".
    # Quantized pools hold ~4x/~4x the pages in the same HBM (fp32
    # compute) with per-PAGE dequant scales stored beside the page table;
    # CoW, prefix sharing, chunked prefill and snapshots operate on
    # quantized pages unchanged. Requires calibration (QuantSpec KV clip
    # ranges) or accepts the engine's automatic one-forward calibration.
    "FLAGS_serving_kv_dtype": "bf16",
    # Route quantized weight GEMMs through the Pallas quant kernel
    # (dequant in the kernel epilogue, fp32 accumulation). TPU-only with
    # Mosaic-friendly shapes, single-chip engines only; everywhere else
    # the same algebra runs as jnp that XLA fuses into the MXU epilogue.
    # Like FLAGS_serving_paged_kernel, the kernel is numerically
    # equivalent but NOT bitwise identical to the jnp epilogue (tiled
    # fp32 accumulation, one rounding under bf16 compute) — disable it
    # when auditing cross-mp-degree bitwise parity of a quantized config
    # on TPU (e.g. restoring an mp snapshot onto a single chip).
    "FLAGS_serving_quant_kernel": True,
    # -- speculative decoding (serving/engine.py + serving/quant.py) --------
    # Speculative multi-token decoding on the paged engine: per boundary a
    # cheap DRAFT pass proposes up to k tokens per slot, then ONE fused
    # verify executable scores all slots at [B,k+1] with per-slot accept
    # masks / lengths / sampling params as traced operands (the chunk-
    # ladder trick: mixed speculative/plain/greedy/sampled traffic shares
    # one executable, admission never retraces). Greedy speculative output
    # is BITWISE identical to the non-speculative engine; sampled streams
    # replay generate_from_params exactly (threefry streams split only on
    # EMITTED tokens). 0 = OFF: the engine builds byte-identical
    # executables to a pre-speculation engine.
    "FLAGS_serving_speculate_k": 0,
    # Draft source: "quant" (default — the PR 14 int8 self-draft: the
    # SAME weights quantized per-channel, reading the engine's paged KV
    # through a draft-scale sidecar; on an already-quantized engine the
    # draft degenerates to the engine weights) or "shallow" (truncate to
    # the first FLAGS_serving_draft_layers transformer blocks — cheaper
    # on CPU where int8 dequant costs more than it saves).
    "FLAGS_serving_draft_source": "quant",
    # Number of transformer blocks the "shallow" draft keeps. 0 = auto
    # (num_layers // 2, at least 1). Ignored by source="quant".
    "FLAGS_serving_draft_layers": 0,
    # -- many-model serving: per-slot LoRA-class adapters (serving/
    # adapters.py). N low-rank deltas of ONE base checkpoint live stacked
    # in fixed-shape device slabs; each slot's adapter id is a TRACED
    # operand of the fused paged step, so a mixed-adapter batch (base
    # model included) shares the engine's two steady-state executables
    # and adapter hot-load/evict/swap are content-only slab rewrites —
    # zero retraces, the swap_params machinery. Attention is never
    # adapted; adapted requests' prefix-cache keys are salted with
    # (adapter id, version) while base traffic shares unsalted keys, so
    # adapter ops skip the prefix-cache flush base-weight swaps require.
    # Loadable adapter slots (ids 1..N; id 0 = base model). 0 = OFF: the
    # engine is byte-identical to the adapter-less one.
    "FLAGS_serving_adapter_slots": 0,
    # Max (padded) adapter rank r: every loaded delta's true rank must be
    # <= this; smaller ranks zero-pad (bitwise-exact — padding columns
    # contribute exact zeros). Static: changing it is a restart, like
    # page_size.
    "FLAGS_serving_adapter_rank": 8,
    # Tenant -> default adapter id mapping, dict ({"acme": 1}) or string
    # ("acme:1,beta:2"): requests that don't name adapter= explicitly are
    # served with their tenant's delta; unmapped tenants get the base
    # model.
    "FLAGS_serving_tenant_adapters": {},
    # -- self-healing serving (serving/engine.py + serving/supervisor.py) ---
    # Engine-snapshot cadence: with a CheckpointManager attached
    # (Engine.attach_checkpoint), every N step boundaries the FULL engine
    # state (KV pool, slot table, PRNG streams, queue, results, metrics)
    # is checkpointed through the hardened CRC/rename-aside path — a cold
    # restart resumes every in-flight request bitwise mid-decode. 0 keeps
    # only the SIGTERM boundary flush.
    "FLAGS_serving_snapshot_every": 32,
    # Per-replica respawn budget for the ServingSupervisor; past it the
    # replica stays down and its unacknowledged requests are replayed on
    # the surviving replicas.
    "FLAGS_serving_max_restarts": 3,
    # Heartbeat staleness threshold (seconds) past which the supervisor
    # declares a replica frozen and fails it over. In topology-elastic
    # mode the same threshold applies to the per-CHIP heartbeat files.
    "FLAGS_serving_heartbeat_timeout": 10.0,
    # -- topology-elastic serving (serving/elastic.py) -----------------------
    # Grow a degraded mp group back to its configured degree when its
    # lost chips return (serving_chip_return_at fires / chip heartbeats
    # recover): a LIVE snapshot handoff — zero drops, zero replays, and
    # zero new traces (builders memoized per (cfg, mesh, rung)). Off:
    # chip losses are sticky, groups only shrink.
    "FLAGS_serving_elastic_grow": True,
    # Bounded router retries while EVERY replica is mid-reform: the
    # supervisor's submit() backs off with a deterministic per-request
    # jitter this many times before raising EngineStoppedError with
    # reforming=True and a retry_after hint.
    "FLAGS_serving_reform_retries": 2,
    # Serving anomaly guard: "off" (default — the fused step and the
    # token trajectory are byte-identical to the unguarded engine) or
    # "quarantine" (a traced per-slot all-finite check on the logits
    # rides the fused paged step; a poisoned slot — NaN/Inf from bad
    # weights, a corrupted KV page or a flaky chip — resolves
    # finish_reason="error" at the boundary, its prompt pages are NOT
    # published to the prefix cache, and its neighbors stay
    # bitwise-stable: the poison never spreads to the shared batch or a
    # snapshot).
    "FLAGS_serving_anomaly_policy": "off",
    # -- disaggregated serving (serving/kv_transfer.py) ----------------------
    # Engine role: "both" (default — the classic single-engine loop that
    # prefills AND decodes), "prefill" (runs only the big-chunk rungs of
    # the chunked-prefill ladder over all slots and streams finished KV
    # pages out — never dispatches the [B,1] decode executable), or
    # "decode" (receives streamed pages between its own decode boundaries
    # and seats them as if the prompt were an exact prefix-cache hit).
    # Role is host-side scheduling policy ONLY: the executables are
    # identical per shape, which is what keeps disaggregated output
    # bitwise equal to a single-engine run. Paged layout required for
    # non-"both" roles. Usually set per-replica via
    # ServingSupervisor(roles=...), not globally.
    "FLAGS_serving_role": "both",
    # Max KV pages a decode worker installs from incoming transfers per
    # step boundary — bounds the host->device copy work that rides
    # between decode dispatches, so an arriving giant-prompt transfer
    # never stalls the decoding slots (T3-style overlap discipline).
    "FLAGS_serving_transfer_pages_per_boundary": 4,
    # Prefix-affinity routing: the supervisor probes each decode
    # replica's prefix cache with the request's cumulative page hashes
    # and routes shared-prefix traffic to the replica that already holds
    # the pages — a hit admits directly on the decode worker and SKIPS
    # the prefill worker and the page transfer entirely. Off: disagg
    # routing is least-loaded-prefill only.
    "FLAGS_serving_affinity_routing": True,
    # -- SLO-driven multi-tenant serving (serving/slo.py) --------------------
    # Class-aware admission: requests carry priority ("interactive" |
    # "batch" | "best_effort") and a tenant id; admission serves classes
    # best-first with weighted fair queueing across tenants WITHIN a class
    # (one tenant cannot starve another), and an interactive request about
    # to miss its deadline preemptively evicts the youngest lowest-class
    # running slot (requeued with its ORIGINAL arrival, the PR 7 drain
    # machinery — its replay is bitwise, so preemption costs latency, never
    # correctness). Default OFF: admission is the strict FCFS the parity
    # suites gate, byte-identical to the pre-SLO engine.
    "FLAGS_serving_priority_classes": False,
    # Per-class default relative deadline (seconds) applied at submit when
    # the request did not set one; 0 = no class deadline. Only read in
    # priority mode.
    "FLAGS_serving_class_deadline_interactive": 0.0,
    "FLAGS_serving_class_deadline_batch": 0.0,
    "FLAGS_serving_class_deadline_best_effort": 0.0,
    # Slack threshold (seconds) under which a queued interactive request
    # counts as about-to-miss-its-deadline and may preempt. 0 = derive from
    # live telemetry (2x the ledger's TTFT p50, floor 50ms).
    "FLAGS_serving_preempt_margin_s": 0.0,
    # Graceful load shedding: when the wait queue sits above
    # shed_high * max_queue for shed_window consecutive step boundaries
    # (sustained overload, not a burst), lowest-class queued work is shed
    # down to shed_low * max_queue with finish_reason="shed" and a
    # retry-after hint derived from the live queue-drain rate — instead of
    # everything timing out. While shedding, NEW lowest-class submissions
    # raise ShedError (same hint). Default OFF.
    "FLAGS_serving_shed": False,
    "FLAGS_serving_shed_high": 0.75,
    "FLAGS_serving_shed_low": 0.5,
    "FLAGS_serving_shed_window": 4,
    # Per-tenant token-bucket rate limit at the supervisor router:
    # sustained requests/second per tenant (0 = off) with a burst
    # allowance. Over-rate submissions raise ShedError with the exact
    # time-to-next-token as retry_after.
    "FLAGS_serving_tenant_rate": 0.0,
    "FLAGS_serving_tenant_burst": 8,
    # Telemetry-driven autoscaling (supervisor): watch fleet queue depth /
    # slot occupancy / TTFT p99 with hysteresis + cooldown and grow/shrink
    # the replica set through the existing spawn/drain machinery. OFF by
    # default; bounds and watermarks below.
    "FLAGS_serving_autoscale": False,
    "FLAGS_serving_min_replicas": 1,
    "FLAGS_serving_max_replicas": 4,
    # Scale up past up_queue waiting requests per live replica (or past
    # up_occupancy mean slot occupancy); scale down below down_queue AND
    # below down_occupancy. Watermarks are deliberately far apart
    # (hysteresis) so the fleet never flaps.
    "FLAGS_serving_autoscale_up_queue": 4.0,
    "FLAGS_serving_autoscale_down_queue": 0.5,
    "FLAGS_serving_autoscale_up_occupancy": 0.9,
    "FLAGS_serving_autoscale_down_occupancy": 0.3,
    # TTFT p99 SLO (seconds) that also triggers scale-up when breached;
    # 0 disables the latency trigger.
    "FLAGS_serving_autoscale_ttft_slo": 0.0,
    # Consecutive over/under-watermark evaluations required before acting,
    # and the minimum wall-clock gap between two actions.
    "FLAGS_serving_autoscale_window": 4,
    "FLAGS_serving_autoscale_cooldown_s": 2.0,
    # Ring-decomposed compute/communication overlap on the mp axis: the
    # pre-QKV/FFN all-gather splits into mp-1 ppermute hops with each
    # chunk's GEMM issued on arrival, and the RowParallel GEMM emits
    # partial products chunk-by-chunk into a pipelined reduce-scatter
    # (T3 / fused computation-collective style). Requires
    # FLAGS_sequence_parallel; default OFF.
    "FLAGS_mp_overlap": False,
    # -- unified telemetry (paddle_tpu/observability) ------------------------
    # Prometheus /metrics endpoint port (stdlib http.server daemon thread
    # over the registry snapshot — observability/prometheus.py). 0 = OFF
    # (the default): nothing binds, nothing is scraped. Set it non-zero
    # BEFORE constructing a serving.Engine or a TrainStep — both bring
    # the endpoint up on construction — or call
    # observability.start_metrics_server(port) directly.
    "FLAGS_metrics_port": 0,
    # Per-request span tracing in the serving engine: every Request
    # records queue-wait, each prefill chunk, decode steps, CoW/prefix
    # events and self-healing hops, survivable through engine snapshots,
    # exportable as Perfetto JSON / JSONL (observability/tracing.py).
    # Host-side only — executables, traced operands and trace counters are
    # untouched either way. Default OFF: untraced requests pay one
    # attribute check.
    "FLAGS_serving_trace": False,
    # Ring-buffer bound on retained finished-request traces.
    "FLAGS_trace_buffer": 4096,
    # Live training-step telemetry (observability/step_telemetry.py):
    # sampled per-step records with dispatch/host-sync wall split,
    # achieved MFU from the static FLOP estimator, wire bytes from the
    # static comm schedules, and device-memory watermarks. Default OFF
    # (one dict lookup per step).
    "FLAGS_step_telemetry": False,
    # Sample every Nth step when step telemetry is on. Sampling blocks on
    # that step's result; the recorded wall time averages over the window
    # since the previous sample, so the number stays honest while
    # unsampled steps keep their async dispatch overlap.
    "FLAGS_step_telemetry_every": 8,
    # EWMA regression sentinel: log a warning when a sampled step's wall
    # time drifts more than this percentage above the rolling baseline.
    # 0 disables the sentinel.
    "FLAGS_step_time_drift_pct": 25.0,
    # -- topology-elastic training (distributed/elastic.py, topology.py) ----
    # Reshard-on-load: a checkpoint whose packed dp-sharded slot layout was
    # produced on a DIFFERENT mesh is resharded for the restoring step
    # (streamed leaf-by-leaf on the host, bitwise round-trippable). Off:
    # a cross-topology load raises TopologyMismatchError naming the
    # differing fields instead (strict fleets that want resumes pinned to
    # the producing topology). Same-topology restores are unaffected
    # either way.
    "FLAGS_elastic_reshard": True,
    # ElasticMeshSupervisor snapshot cadence (TrainStep.attach_checkpoint
    # save_every): the newest good snapshot is what a re-formed mesh
    # resumes from, so this bounds steps re-executed after a chip loss.
    "FLAGS_elastic_snapshot_every": 4,
    # Smallest dp the supervisor will shrink to before giving up.
    "FLAGS_elastic_min_dp": 1,
    # Grow the mesh back when failed ranks return (heartbeats recover /
    # chip_return_at fires). Off: failures are sticky, the mesh only
    # shrinks.
    "FLAGS_elastic_grow": True,
    # Heartbeat staleness threshold (seconds) for the supervisor's rank
    # failure detection when a heartbeat_dir is configured.
    "FLAGS_elastic_heartbeat_timeout": 5.0,
    # -- per-axis communication-schedule backend ----------------------------
    # Pluggable collective decomposition per mesh axis, e.g. "mp=fused" or
    # "mp=fused,dp=ring" (distributed/comm_backend.py). Backends:
    #   gspmd — the partitioner emits whole collectives (seed behavior);
    #   ring  — scheduling-level overlap: mp-1 ppermute hops with chunk
    #           GEMMs on arrival (PR 3's ring_ag_gemm/gemm_ring_rs for mp;
    #           grad_comm's explicit bucketed RS/AG schedule for dp);
    #   fused — kernel-level fusion: Pallas kernels whose grid steps DMA
    #           the next remote chunk while the current chunk's tile GEMM
    #           runs, and whose reduce-scatter epilogue accumulates partial
    #           tiles directly into the scatter destination — no
    #           intermediate full-size buffer is ever materialized
    #           (ops/pallas_kernels/fused_collectives.py).
    # Naming mp=ring/fused implies the sequence-parallel activation layout;
    # naming dp=ring/fused implies the explicit grad-comm schedule. The
    # pp axis selects the PIPELINE-boundary schedule (distributed/
    # pipeline.py): pp=gspmd keeps the seed's partial-manual pipeline;
    # pp=ring rewrites the gpipe/1f1b schedule fully manually with the
    # boundary activation/cotangent ppermutes issued at the end of each
    # scan tick (the hop rides the wire while the next tick's stage GEMMs
    # run, and the partitioner never sees a replicated stage select —
    # involuntary-remat warnings die structurally); pp=fused additionally
    # runs each stage's LAST GEMM as a Pallas kernel whose epilogue issues
    # the boundary RDMA directly (fused_collectives.fused_gemm_ppsend,
    # custom VJP for the backward tick). The empty default keeps the
    # legacy flags in charge (FLAGS_mp_overlap -> mp=ring,
    # FLAGS_grad_comm/FLAGS_weight_update_sharding -> dp=ring) and the
    # flags-off program byte-identical to the seed. Ineligible selections
    # fall back one rung (fused -> ring -> gspmd) with a once-per-reason
    # warning naming the exact flag that would fix it.
    "FLAGS_comm_backend": "",
    # Boundary wire dtype of the explicit pp schedule (grad_comm's wire
    # vocabulary: "auto" | "float32" | "bfloat16"). "auto" wires the
    # compute dtype; "bfloat16" halves boundary bytes while every stage
    # still accumulates fp32 (pp=fused ignores this — its RDMA leaves the
    # GEMM epilogue at the compute dtype).
    "FLAGS_pp_wire_dtype": "auto",
    # -- silent-data-corruption sentinel (distributed/integrity.py) ---------
    # Fuse a per-replica integrity fingerprint (uint32 bit-reduction over
    # params + replicated optimizer slots) into every Nth step executable
    # and cross-check it over the dp axis: a flipped bit in ONE replica's
    # copy shows up as a fingerprint minority, is localized by majority
    # vote, and is repaired in place from a healthy peer's bytes — no disk
    # rewind, zero steps lost. The verdict rides the step's existing
    # combined host fetch (host_syncs per update step unchanged; the
    # fault_counters ledger audits it). 0 = OFF (the default): the step
    # executable is byte-identical to flags-off.
    "FLAGS_sdc_check_every": 0,
    # Peer repairs charged to one rank before the rank is declared a
    # repeat offender: integrity.quarantined_ranks() reports it and the
    # ElasticMeshSupervisor (policy "quarantine") treats it as a lost
    # chip — the PR 11 reform path, not a fleet-wide disk rewind.
    "FLAGS_sdc_quarantine_threshold": 2,
    # Serving shadow audit: this fraction of FINISHED requests (chosen
    # deterministically from the request id) is replayed through
    # generate_from_params and bitwise-compared before the result is
    # delivered. A mismatch refuses delivery, replays the request, and
    # bumps the owning replica's suspicion score. 0.0 = OFF.
    "FLAGS_serving_audit_rate": 0.0,
    # Audit failures charged to one replica before the supervisor fails
    # it over (fresh engine; the corrupted KV pool and prefix cache are
    # discarded before corruption spreads through cached prefixes).
    "FLAGS_serving_audit_threshold": 2,
    # CRC32 end-to-end checksums on disaggregated KV-transfer page
    # payloads (page bytes + quant scale columns, stamped at stream time,
    # verified before install). A mismatched page refuses the transfer;
    # the supervisor re-offers the retained clean payload. Default OFF:
    # payloads carry crc=None and verification is a no-op.
    "FLAGS_kv_transfer_crc": False,
    # Background checkpoint scrub cadence: every Nth save, re-verify the
    # retained snapshots' CRC manifests from _prune and quarantine rot
    # (*.corrupt) BEFORE restore time needs them. 0 = OFF.
    "FLAGS_ckpt_scrub_every": 0,
}


def set_flags(flags: dict):
    for k, v in flags.items():
        _FLAGS[k] = v


def get_flags(flags=None):
    if flags is None:
        return dict(_FLAGS)
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}
