"""Framework flags (ref: FLAGS_* in paddle/fluid/framework + paddle.set_flags).

TPU-relevant knobs only; unknown flags are stored and returned verbatim so
scripts written against the reference don't crash.
"""
from __future__ import annotations

_FLAGS = {
    "FLAGS_use_flash_attention": True,
    "FLAGS_cudnn_deterministic": False,   # accepted, no-op on TPU
    "FLAGS_embedding_deterministic": False,
    "FLAGS_use_remat": False,
    "FLAGS_matmul_precision": "default",  # default|highest (f32 on MXU)
    "FLAGS_donate_buffers": True,
    # Eager dispatch cache: route repeat op dispatches through cached
    # jax.jit executables (dispatch.py). Disable to force op-by-op eager
    # execution when debugging numerics or tracing issues.
    "FLAGS_eager_jit_cache": True,
    # Persist XLA executables across processes (JAX_COMPILATION_CACHE_DIR,
    # default <cwd>/.jax_cache — see framework/compilation_cache.py).
    "FLAGS_persistent_compilation_cache": True,
}


def set_flags(flags: dict):
    for k, v in flags.items():
        _FLAGS[k] = v


def get_flags(flags=None):
    if flags is None:
        return dict(_FLAGS)
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}
