"""`paddle.linalg` namespace (ref: python/paddle/linalg.py — a re-export of
tensor.linalg)."""
from .tensor.linalg import (  # noqa: F401
    cholesky, norm, cond, cov, corrcoef, inv, eig, eigvals, multi_dot,
    matrix_rank, svd, svdvals, qr, lu, lu_unpack, matrix_power, matrix_exp,
    det, slogdet, eigh, eigvalsh, pinv, solve, cholesky_solve,
    triangular_solve, lstsq, householder_product, vector_norm, matrix_norm,
)

__all__ = [
    "cholesky", "norm", "cond", "cov", "corrcoef", "inv", "eig", "eigvals",
    "multi_dot", "matrix_rank", "svd", "qr", "lu", "lu_unpack",
    "matrix_power", "det", "slogdet", "eigh", "eigvalsh", "pinv", "solve",
    "cholesky_solve", "triangular_solve", "lstsq", "svdvals", "matrix_exp",
    "householder_product", "vector_norm", "matrix_norm",
]
