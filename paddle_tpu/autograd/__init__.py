"""paddle_tpu.autograd — eager autograd API.

Parity with python/paddle/autograd: backward, grad, no_grad, PyLayer.
Functional transforms (jacobian/hessian/vjp/jvp) ride jax directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.state import no_grad, enable_grad, set_grad_enabled, grad_enabled
from ..tensor_impl import Tensor, as_tensor_data
from .node import GradNode
from .engine import backward, backward_multi, grad, register_tensor_hook

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "PyLayer", "PyLayerContext", "jacobian", "hessian", "vjp", "jvp",
]


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    def mark_not_inplace(self, *a):  # API parity no-ops (no aliasing on XLA)
        pass

    def mark_non_differentiable(self, *a):
        pass

    def set_materialize_grads(self, v):
        self._materialize_grads = bool(v)


class PyLayer:
    """Custom op with user-defined backward (ref: python/paddle/autograd/py_layer.py).

    class Tanh(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle_tpu.tanh(x); ctx.save_for_backward(y); return y
        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor(); return dy * (1 - y * y)
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_parents = [a for a in args if isinstance(a, Tensor)]
        needs = grad_enabled() and any(not t.stop_gradient for t in tensor_parents)
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        if not needs:
            return outputs
        leaves, treedef = jax.tree_util.tree_flatten(
            outputs, is_leaf=lambda x: isinstance(x, Tensor))
        avals = [jax.ShapeDtypeStruct(tuple(l.shape), l.dtype) for l in leaves]

        def vjp_fn(cot_struct):
            cot_leaves, _ = jax.tree_util.tree_flatten(cot_struct)
            cot_tensors = [
                Tensor(c) if not (isinstance(c, np.ndarray) and c.dtype == jax.dtypes.float0)
                else None for c in cot_leaves]
            cot_tensors = [c for c in cot_tensors if c is not None]
            with no_grad():
                gs = cls.backward(ctx, *cot_tensors)
            if not isinstance(gs, (tuple, list)):
                gs = (gs,)
            out = []
            for g in gs:
                out.append(None if g is None else as_tensor_data(g))
            # pad/truncate to parent count
            out = (list(out) + [None] * len(tensor_parents))[: len(tensor_parents)]
            return tuple(out)

        node = GradNode(vjp_fn, tensor_parents, treedef, avals, op_name=cls.__name__)
        new_leaves = []
        for i, l in enumerate(leaves):
            t = Tensor(l._data, stop_gradient=False)
            t._node = node
            t._out_idx = i
            new_leaves.append(t)
        return jax.tree_util.tree_unflatten(treedef, new_leaves)


# -- functional transforms (thin jax bridges) --------------------------------
def _to_pure(func):
    def pure(*arrays):
        tensors = [Tensor(a, stop_gradient=False) for a in arrays]
        out = func(*tensors)
        return jax.tree_util.tree_map(
            as_tensor_data, out, is_leaf=lambda x: isinstance(x, Tensor))
    return pure


def jacobian(func, xs, create_graph=False):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [as_tensor_data(x) for x in xs_list]
    jac = jax.jacrev(_to_pure(func), argnums=tuple(range(len(arrays))))(*arrays)
    wrapped = jax.tree_util.tree_map(Tensor, jac)
    return wrapped if isinstance(xs, (list, tuple)) else (
        wrapped[0] if isinstance(wrapped, tuple) else wrapped)


def hessian(func, xs, create_graph=False):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [as_tensor_data(x) for x in xs_list]
    h = jax.hessian(_to_pure(func), argnums=tuple(range(len(arrays))))(*arrays)
    wrapped = jax.tree_util.tree_map(Tensor, h)
    return wrapped if isinstance(xs, (list, tuple)) else (
        wrapped[0] if isinstance(wrapped, tuple) else wrapped)


def vjp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [as_tensor_data(x) for x in xs_list]
    out, pullback = jax.vjp(_to_pure(func), *arrays)
    if v is None:
        v_arr = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_arr = jax.tree_util.tree_map(
            as_tensor_data, v, is_leaf=lambda x: isinstance(x, Tensor))
    grads = pullback(v_arr)
    return (jax.tree_util.tree_map(Tensor, out),
            jax.tree_util.tree_map(Tensor, grads if isinstance(xs, (list, tuple)) else grads[0]))


def jvp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [as_tensor_data(x) for x in xs_list]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        v_list = v if isinstance(v, (list, tuple)) else [v]
        tangents = [as_tensor_data(t) for t in v_list]
    out, tangent_out = jax.jvp(_to_pure(func), tuple(arrays), tuple(tangents))
    return (jax.tree_util.tree_map(Tensor, out), jax.tree_util.tree_map(Tensor, tangent_out))


class saved_tensors_hooks:
    """Pack/unpack hooks for tape-saved tensors (ref: python/paddle/autograd/
    saved_tensors_hooks.py — used for CPU offload / compression of saved
    activations).

    TPU-native scope: the jax.vjp residual closure is opaque, but every
    GradNode also retains its primal inputs (`primals`, used for
    double-backward). Inside this context those retained primals run
    through pack_hook at record time and unpack_hook at backward time —
    the mechanism reference users rely on to offload/quantize retained
    activations. The preferred TPU memory lever remains jax.checkpoint
    (recompute), which trades the residual memory away entirely.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from ..framework import state as _st
        self._prev = getattr(_st._state, "saved_tensor_hooks", None)
        _st._state.saved_tensor_hooks = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        from ..framework import state as _st
        _st._state.saved_tensor_hooks = self._prev
        return False


__all__.append("saved_tensors_hooks")
