"""Tape node for eager autograd.

Re-design of the reference's per-op GradNode graph (ref: paddle/fluid/eager/
grad_node_info.h). One node per dispatched op; holds the jax.vjp pullback
(which owns the saved residuals) and edges to parent tensors.
"""
from __future__ import annotations


class GradNode:
    __slots__ = ("vjp_fn", "parents", "out_treedef", "out_avals", "op_name", "hooks",
                 "fwd_fn", "primals", "saved_unpack", "vjp_cached")

    def __init__(self, vjp_fn, parents, out_treedef, out_avals, op_name=None,
                 fwd_fn=None, primals=None):
        self.vjp_fn = vjp_fn          # cotangent-pytree -> tuple(input cotangents)
        self.parents = parents        # list[Tensor | None], aligned with vjp inputs
        self.out_treedef = out_treedef
        self.out_avals = out_avals    # list[ShapeDtypeStruct] per output leaf
        self.op_name = op_name
        self.hooks = None             # {out_idx: [hook]}
        # For double-backward (create_graph=True): re-derive the pullback as a
        # traced op over (primals, cotangents). fwd_fn is the pure forward
        # closure; primals the original input arrays.
        self.fwd_fn = fwd_fn
        self.primals = primals
        self.saved_unpack = None      # saved_tensors_hooks unpack fn
        # True when vjp_fn is a jit-returned tree_util.Partial from the
        # dispatch cache (stable treedef -> jit-cacheable backward).
        self.vjp_cached = False

    def get_primals(self):
        """Retained primal inputs, routed through the saved_tensors_hooks
        unpack fn when one was active at record time."""
        if self.saved_unpack is None or self.primals is None:
            return self.primals
        import jax.numpy as jnp
        return [jnp.asarray(self.saved_unpack(p)) for p in self.primals]

    def add_hook(self, out_idx, hook):
        if self.hooks is None:
            self.hooks = {}
        self.hooks.setdefault(out_idx, []).append(hook)

    def __repr__(self):
        return f"GradNode({self.op_name}, n_parents={len(self.parents)})"
