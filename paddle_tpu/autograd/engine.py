"""Backward engine: reverse-topological walk over the eager tape.

Re-design of the reference's backward engine (ref: paddle/fluid/eager/
backward.cc `RunBackward`): instead of C++ grad-op kernels we call the stored
jax.vjp pullbacks; XLA executes the pullback computations on device.

Cotangents flow through the walk as Tensors. With `create_graph=True` each
pullback is re-derived via jax.vjp over (primals, cotangents) and dispatched
through `dispatch.apply`, so computed gradients carry their own tape edges back
to both the primal inputs and the incoming cotangents (full higher-order
support, e.g. grad-of-grad).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor_impl import Tensor
from ..framework import state as _st

def register_tensor_hook(t: Tensor, hook):
    """paddle Tensor.register_hook parity. Hook: grad_tensor -> grad_tensor|None.

    Leaf hooks live on the tensor object itself (Tensor.__eq__ is elementwise,
    so Tensors cannot key a dict)."""
    if t._node is not None:
        t._node.add_hook(t._out_idx, hook)
    else:
        if not hasattr(t, "_leaf_hooks"):
            t._leaf_hooks = []
        t._leaf_hooks.append(hook)

    class _Handle:
        def remove(self_inner):
            if t._node is not None and t._node.hooks:
                hooks = t._node.hooks.get(t._out_idx, [])
                if hook in hooks:
                    hooks.remove(hook)
            elif hook in getattr(t, "_leaf_hooks", []):
                t._leaf_hooks.remove(hook)

    return _Handle()


def _is_float0(x):
    """Canonical float0 check (dispatch.py imports this one — keep single)."""
    return isinstance(x, np.ndarray) and x.dtype == jax.dtypes.float0


def _zeros_cot(aval):
    """Materialized zero cotangent — higher-order path only; the first-order
    path uses dispatch.SymbolicZero markers resolved inside the compiled
    backward instead of allocating real buffers."""
    if jnp.issubdtype(aval.dtype, jnp.floating) or jnp.issubdtype(aval.dtype, jnp.complexfloating):
        return jnp.zeros(aval.shape, aval.dtype)
    return np.zeros(aval.shape, jax.dtypes.float0)


def _acc_many(terms):
    """Fuse all pending cotangent contributions for one tape slot.

    Tape-free terms (the create_graph=False common case) sum in ONE jitted
    n-ary add — a single compiled program and output buffer per slot instead
    of a chain of pairwise eager adds. Terms carrying a tape (create_graph
    or hook-produced) keep pairwise dispatched adds so the accumulation
    itself stays differentiable."""
    terms = [t for t in terms if t is not None]
    if not terms:
        return None
    if len(terms) == 1:
        return terms[0]
    if any(t._node is not None for t in terms):
        from ..dispatch import apply
        out = terms[0]
        for t in terms[1:]:
            out = apply(jnp.add, out, t, op_name="grad_acc")
        return out
    from ..dispatch import fused_accumulate
    return Tensor(fused_accumulate([t._data for t in terms]))


def _topo_order(root_nodes):
    """Reverse-topological order via iterative postorder DFS."""
    visited, order = set(), []
    for root in root_nodes:
        if root is None or id(root) in visited:
            continue
        stack = [(root, False)]
        while stack:
            node, processed = stack.pop()
            if processed:
                order.append(node)
                continue
            if id(node) in visited:
                continue
            visited.add(id(node))
            stack.append((node, True))
            for p in node.parents:
                if p is not None and p._node is not None and id(p._node) not in visited:
                    stack.append((p._node, False))
    return list(reversed(order))


def _call_vjp(node, cots, create_graph):
    """cots: {out_idx: Tensor}. Returns list of Tensor|None aligned with parents."""
    if not create_graph:
        # Missing cotangents stay SYMBOLIC: markers carry (shape, dtype) in
        # the pytree structure and the zeros materialize inside the jitted
        # backward (XLA folds them) — or eagerly, for uncached pullbacks.
        # Cotangents arriving in a different float dtype than the recorded
        # output aval (AMP white->black boundaries: an fp32 softmax grad
        # meeting a bf16 matmul output) are cast to the output's dtype,
        # matching the reference's grad-dtype-follows-output semantics.
        from ..dispatch import run_pullback, symbolic_zero_for
        leaves = []
        for i, av in enumerate(node.out_avals):
            c = cots.get(i)
            if c is None:
                leaves.append(symbolic_zero_for(av))
            else:
                d = c._data
                if d.dtype != av.dtype and jnp.issubdtype(
                        av.dtype, jnp.inexact):
                    d = d.astype(av.dtype)
                leaves.append(d)
        struct = jax.tree_util.tree_unflatten(node.out_treedef, leaves)
        with _st.no_grad():
            raw = run_pullback(node, struct)
        out = []
        for g in raw:
            out.append(None if g is None or _is_float0(g) else Tensor(g))
        return out

    full = []
    for i, av in enumerate(node.out_avals):
        c = cots.get(i)
        if c is None:
            full.append(_zeros_cot(av))
        else:
            if c._data.dtype != av.dtype and jnp.issubdtype(
                    av.dtype, jnp.inexact):
                from ..dispatch import apply as _dispatch_apply
                dt = jnp.dtype(av.dtype).name
                c = _dispatch_apply(lambda a: a.astype(dt), c,
                                    op_name="grad_cast")
            full.append(c)

    # Higher-order path: re-derive pullback over (primals, cotangents).
    if node.fwd_fn is None:
        raise RuntimeError(
            f"Op {node.op_name} does not support create_graph=True (custom PyLayer "
            "without double-backward).")
    tensor_parent_ix = [i for i, p in enumerate(node.parents) if p is not None]
    real_cot_ix = [i for i, c in enumerate(full) if isinstance(c, Tensor)]
    raw_leaves = [c._data if isinstance(c, Tensor) else c for c in full]
    primals0 = node.get_primals()
    treedef = node.out_treedef
    fwd = node.fwd_fn

    def fn(*args):
        k = len(tensor_parent_ix)
        primals = list(primals0)
        for j, pi in enumerate(tensor_parent_ix):
            primals[pi] = args[j]
        leaves = list(raw_leaves)
        for j, ci in enumerate(real_cot_ix):
            leaves[ci] = args[k + j]
        _, vjp_fn = jax.vjp(fwd, *primals)
        gs = vjp_fn(jax.tree_util.tree_unflatten(treedef, leaves))
        # drop float0s (non-differentiable inputs) — they confuse tree wrapping
        return tuple(g for g in gs if not _is_float0(g))

    inputs = [node.parents[i] for i in tensor_parent_ix] + [full[i] for i in real_cot_ix]
    from ..dispatch import apply
    outs = apply(fn, *inputs, op_name=f"{node.op_name}_grad")
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    # re-align to parents: float0 slots (non-float primals) were dropped
    aligned, it = [], iter(outs)
    for i, p in enumerate(node.parents):
        a = primals0[i]
        diff = hasattr(a, "dtype") and (
            jnp.issubdtype(a.dtype, jnp.floating) or jnp.issubdtype(a.dtype, jnp.complexfloating))
        if diff:
            aligned.append(next(it, None))
        else:
            aligned.append(None)
    return aligned


def run_backward(roots, seeds, retain_graph=False, create_graph=False):
    """Core walk. roots: list[Tensor]; seeds: list[Tensor] same length.
    Accumulates into leaf .grad."""
    _walk(roots, seeds, retain_graph, create_graph, inputs=None, accumulate=True)


def _walk(roots, seeds, retain_graph, create_graph, inputs, accumulate):
    targets = {}
    results = [[] for _ in range(len(inputs) if inputs else 0)]
    leaf_inputs = {}
    if inputs:
        for i, t in enumerate(inputs):
            if t._node is not None:
                targets.setdefault((id(t._node), t._out_idx), []).append(i)
            else:
                leaf_inputs.setdefault(id(t), []).append(i)

    # Pending contributions accumulate as LISTS and fuse once, when the node
    # (or leaf) is consumed — one compiled multi-accumulate per slot instead
    # of a chain of pairwise adds.
    store = {}  # id(node) -> {out_idx: [Tensor, ...]}
    node_by_id = {}
    leaf_grads = {}  # id(tensor) -> (tensor, [Tensor, ...])

    def add_leaf(t, g):
        if g is None:
            return
        leaf_grads.setdefault(id(t), (t, []))[1].append(g)

    root_nodes = []
    for t, seed in zip(roots, seeds):
        if t._node is None:
            if inputs and id(t) in leaf_inputs:
                for i in leaf_inputs[id(t)]:
                    results[i].append(seed)
            if accumulate and not t.stop_gradient:
                add_leaf(t, seed)
            continue
        node_by_id[id(t._node)] = t._node
        store.setdefault(id(t._node), {}).setdefault(
            t._out_idx, []).append(seed)
        root_nodes.append(t._node)

    order = _topo_order(root_nodes)

    for node in order:
        slots = store.pop(id(node), None)
        if slots is None:
            continue
        if node.vjp_fn is None and node.fwd_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time; the saved "
                "intermediate results were freed. Pass retain_graph=True.")
        cots = {}
        for idx, terms in slots.items():
            fused = _acc_many(terms)
            if fused is not None:
                cots[idx] = fused
        if node.hooks:
            for idx, hooks in node.hooks.items():
                if idx in cots and cots[idx] is not None:
                    for h in hooks:
                        out = h(cots[idx])
                        if out is not None:
                            cots[idx] = out if isinstance(out, Tensor) else Tensor(out)
        # harvest interior targets
        for idx, cot in cots.items():
            key = (id(node), idx)
            if key in targets and cot is not None:
                for i in targets[key]:
                    results[i].append(cot)
        in_cots = _call_vjp(node, cots, create_graph)
        if not retain_graph and not create_graph:
            node.vjp_fn = None
            node.fwd_fn = None
            node.primals = None
        for parent, g in zip(node.parents, in_cots):
            if parent is None or g is None:
                continue
            if parent._node is None:
                if inputs and id(parent) in leaf_inputs:
                    for i in leaf_inputs[id(parent)]:
                        results[i].append(g)
                if accumulate and not parent.stop_gradient:
                    add_leaf(parent, g)
            else:
                store.setdefault(id(parent._node), {}).setdefault(
                    parent._out_idx, []).append(g)

    for t, terms in leaf_grads.values():
        g = _acc_many(terms)
        if g is None:
            continue
        for h in getattr(t, "_leaf_hooks", []):
            out = h(g)
            if out is not None:
                g = out if isinstance(out, Tensor) else Tensor(out)
        if t._grad is None:
            t._grad = g
        else:
            t._grad = _acc_many([t._grad, g])
        if not create_graph:
            t._grad.stop_gradient = True
    return [_acc_many(r) for r in results]


def backward(tensor, grad_tensor=None, retain_graph=False):
    """Tensor.backward(): seed with ones (any shape, paddle semantics)."""
    if tensor.stop_gradient and tensor._node is None:
        return
    if grad_tensor is None:
        seed = Tensor(jnp.ones(tensor._data.shape, tensor._data.dtype))
    elif isinstance(grad_tensor, Tensor):
        seed = grad_tensor
    else:
        seed = Tensor(jnp.asarray(grad_tensor).astype(tensor._data.dtype))
    run_backward([tensor], [seed], retain_graph=retain_graph)


def backward_multi(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity."""
    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    seeds = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            seeds.append(Tensor(jnp.ones(t._data.shape, t._data.dtype)))
        else:
            seeds.append(g if isinstance(g, Tensor) else Tensor(jnp.asarray(g)))
    run_backward(tensors, seeds, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad parity (ref: python/paddle/fluid/dygraph/base.py::grad)."""
    single_out = not isinstance(outputs, (list, tuple))
    outputs = [outputs] if single_out else list(outputs)
    inputs_list = [inputs] if not isinstance(inputs, (list, tuple)) else list(inputs)
    if retain_graph is None:
        retain_graph = create_graph
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    seeds = []
    for t, g in zip(outputs, grad_outputs):
        if g is None:
            seeds.append(Tensor(jnp.ones(t._data.shape, t._data.dtype)))
        else:
            seeds.append(g if isinstance(g, Tensor) else Tensor(jnp.asarray(g)))
    collected = _walk(outputs, seeds, retain_graph, create_graph,
                      inputs=inputs_list, accumulate=False)
    res = []
    for g in collected:
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused in the graph. "
                    "Set allow_unused=True if this is intended.")
            res.append(None)
        else:
            if not create_graph:
                g.stop_gradient = True
            res.append(g)
    return res
