"""AMP (ref: python/paddle/amp/auto_cast.py, grad_scaler.py).

auto_cast sets a dtype policy consulted by op dispatch (white ops run in
bf16/fp16 feeding the MXU, black ops in fp32). On TPU the native mixed
precision dtype is bfloat16 — no loss scaling needed — but GradScaler
implements the full fp16 algebra for parity.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from ..framework import state as _st
from ..framework.state import to_jnp_dtype
from ..tensor_impl import Tensor, Parameter
from ..dispatch import WHITE_OPS, BLACK_OPS


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    st = _st._state
    prev = (st.amp_level, st.amp_dtype, st.amp_custom_white, st.amp_custom_black)
    if enable:
        st.amp_level = level
        st.amp_dtype = to_jnp_dtype(dtype)
        st.amp_custom_white = set(custom_white_list or ())
        st.amp_custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (st.amp_level, st.amp_dtype, st.amp_custom_white, st.amp_custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to the amp dtype; optimizer gets master weights
    (ref amp.decorate)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        d = to_jnp_dtype(dtype)
        for m in model_list:
            for _, p in m.named_parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._data = p._data.astype(d)
        if optimizers is not None:
            opt_list = [optimizers] if not isinstance(optimizers, (list, tuple)) \
                else list(optimizers)
            for o in opt_list:
                o._multi_precision = True if master_weight is None else master_weight
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var):
        if not self._enable:
            return var
        # scaling the next iteration's loss opens a new step: re-arm the
        # unscale_ guard here as well as in update(), so loops that call
        # optimizer.step() directly (no scaler.step()/update()) still get
        # their grads unscaled exactly once per iteration. NB the reference
        # contract requires ALL scaled backwards to precede unscale_ within
        # a step — scale() after unscale_ in the same step accumulates
        # scaled grads onto unscaled ones and is invalid either way
        self._unscaled = False
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if self._unscaled:
            # already unscaled this step: a second call (user unscale_ for
            # grad clipping followed by scaler.step, which unscales
            # internally) must be a no-op until update()/the next scale()
            # opens a new step — matching the reference's per-step
            # unscaling cache; silently dividing by the scale twice
            # corrupts every gradient
            return
        self._unscaled = True
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        # one device-side reduction over all grads, one host sync at the end
        # (per-param bool() forced a device->host round trip per parameter)
        finite_parts = []
        for p in params:
            if p._grad is None:
                continue
            g = p._grad._data.astype(jnp.float32) * inv
            finite_parts.append(jnp.all(jnp.isfinite(g)))
            p._grad._data = g
        if finite_parts:
            all_finite = jnp.stack(finite_parts).all()
            self._found_inf = not bool(all_finite)
        else:
            self._found_inf = False

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        self._unscaled = False  # close the step: unscale_ re-arms
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


# white/black list introspection parity
def white_list():
    return {"float16": {"O1": sorted(WHITE_OPS)}, "bfloat16": {"O1": sorted(WHITE_OPS)}}


def black_list():
    return {"float16": {"O1": sorted(BLACK_OPS)}, "bfloat16": {"O1": sorted(BLACK_OPS)}}


def is_float16_supported(device=None):
    """fp16 compute is supported on every XLA backend; on TPU bf16 is the
    preferred half type (MXU-native)."""
    return True


def is_bfloat16_supported(device=None):
    import jax
    return jax.default_backend() in ("tpu", "cpu")


from . import debugging  # noqa: E402,F401
from .debugging import (  # noqa: E402,F401
    DebugMode, TensorCheckerConfig, check_numerics, collect_operator_stats,
    compare_accuracy, disable_operator_stats_collection,
    disable_tensor_checker, enable_operator_stats_collection,
    enable_tensor_checker,
)
