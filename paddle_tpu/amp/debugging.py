"""AMP debugging tools (ref: python/paddle/amp/debugging.py).

The reference instruments C++ kernels; here the eager dispatch layer
(dispatch.apply) is the single chokepoint, so the tensor checker and
operator-stats collector hook there: every dispatched op can have its
outputs nan/inf-checked on host and its (op, dtype) call count recorded.
Compiled (jit) paths are outside the eager tape — for those, NanGuard
(distributed/elastic.py) checks the step outputs instead.
"""
from __future__ import annotations

import contextlib
from collections import Counter

import numpy as np
import jax

from ..framework import state as _st


class DebugMode:
    """ref amp/debugging.py DebugMode."""
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    DUMP_ALL = 4


class TensorCheckerConfig:
    """ref amp/debugging.py TensorCheckerConfig."""

    def __init__(self, enable=False, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or ())
        self.skipped_op_list = set(skipped_op_list or ())
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit


def enable_tensor_checker(checker_config):
    _st._state.amp_tensor_checker = checker_config if \
        getattr(checker_config, "enable", True) else None


def disable_tensor_checker():
    _st._state.amp_tensor_checker = None


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Host-side nan/inf check of one tensor (ref check_numerics op).
    Returns (num_nan, num_inf, num_zero) like the reference kernel."""
    from ..tensor_impl import as_tensor_data
    arr = np.asarray(jax.device_get(as_tensor_data(tensor)))
    if not np.issubdtype(arr.dtype, np.floating):
        return 0, 0, int((arr == 0).sum())
    n_nan = int(np.isnan(arr).sum())
    n_inf = int(np.isinf(arr).sum())
    n_zero = int((arr == 0).sum())
    mode = debug_mode if debug_mode is not None else \
        DebugMode.CHECK_NAN_INF_AND_ABORT
    if (n_nan or n_inf) and mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise RuntimeError(
            f"check_numerics: op={op_type!r} var={var_name!r} has "
            f"{n_nan} nan / {n_inf} inf values")
    return n_nan, n_inf, n_zero


def _checker_hook(op_name, leaves):
    """Called by dispatch.apply on eager op outputs when a checker is on."""
    cfg = getattr(_st._state, "amp_tensor_checker", None)
    if cfg is not None:
        if cfg.checked_op_list and op_name not in cfg.checked_op_list:
            pass
        elif op_name in cfg.skipped_op_list:
            pass
        else:
            for leaf in leaves:
                if hasattr(leaf, "dtype") and np.issubdtype(
                        np.dtype(leaf.dtype), np.floating):
                    check_numerics(leaf, op_type=op_name or "",
                                   debug_mode=cfg.debug_mode)
    stats = getattr(_st._state, "amp_op_stats", None)
    if stats is not None:
        for leaf in leaves:
            dt = str(getattr(leaf, "dtype", "?"))
            stats[f"{op_name or 'unknown'}-{dt}"] += 1


def enable_operator_stats_collection():
    _st._state.amp_op_stats = Counter()


def disable_operator_stats_collection():
    stats = getattr(_st._state, "amp_op_stats", None)
    _st._state.amp_op_stats = None
    if stats:
        _print_stats(stats)
    return stats


def _print_stats(stats):
    print("<------------------------------ op list ------------------------->")
    for key in sorted(stats):
        print(f"  {key}: {stats[key]}")
    print("<----------------------------------- done ----------------------->")


@contextlib.contextmanager
def collect_operator_stats():
    """ref amp/debugging.py collect_operator_stats context manager."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Compare two op-stats/tensor dumps (ref compare_accuracy — the
    reference diffs fp32-vs-fp16 run workerlogs). Accepts paths to files
    written as repr(dict) / one 'key: count' per line; writes a csv of
    keys whose counts differ."""
    def read(path):
        out = {}
        with open(path) as f:
            for line in f:
                if ":" in line:
                    k, _, v = line.rpartition(":")
                    try:
                        out[k.strip()] = int(v)
                    except ValueError:
                        pass
        return out

    a, b = read(dump_path), read(another_dump_path)
    rows = ["key,run_a,run_b"]
    for k in sorted(set(a) | set(b)):
        if a.get(k) != b.get(k):
            rows.append(f"{k},{a.get(k, 0)},{b.get(k, 0)}")
    with open(output_filename, "w") as f:
        f.write("\n".join(rows) + "\n")
    return output_filename
