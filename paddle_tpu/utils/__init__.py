"""Utilities (ref: python/paddle/utils/__init__.py — deprecated decorator,
try_import lazy imports, unique_name, dlpack, run_check)."""
from __future__ import annotations

import functools
import importlib
import threading
import warnings

__all__ = ["deprecated", "run_check", "require_version", "try_import"]


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (warns once per call site)."""

    def decorator(func):
        msg = f"API `{func.__module__}.{func.__name__}` is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use `{update_to}` instead"
        if reason:
            msg += f". Reason: {reason}"
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            # level 0/1: warn at call time; level 2: the API is removed and
            # calling it is an error (decoration itself stays harmless)
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__deprecated_message__ = msg
        return wrapper

    return decorator


def try_import(module_name, err_msg=None):
    """Import an optional dependency with a friendly error."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"Failed importing {module_name}. Please install it "
                          f"to use this functionality.")


def require_version(min_version, max_version=None):
    """Check the installed paddle_tpu version is within [min, max]."""
    from .. import __version__

    def as_tuple(v):
        return tuple(int(p) for p in str(v).split(".")[:3])

    cur = as_tuple(__version__)
    if as_tuple(min_version) > cur or (max_version and as_tuple(max_version) < cur):
        raise Exception(
            f"paddle_tpu version {__version__} does not satisfy "
            f"[{min_version}, {max_version or 'any'}]")


def run_check():
    """Smoke-check the install: one matmul on the default backend, and a
    sharded matmul when multiple devices are present (ref:
    utils/install_check.py)."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((128, 128), jnp.float32)
    y = jax.jit(lambda a: a @ a)(x)
    assert float(y[0, 0]) == 128.0
    n = jax.device_count()
    if n > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        import numpy as np
        mesh = Mesh(np.array(jax.devices()).reshape(n), ("x",))
        xs = jax.device_put(x, NamedSharding(mesh, PartitionSpec("x")))
        jax.jit(lambda a: a @ a.T)(xs).block_until_ready()
    print(f"paddle_tpu is installed successfully! "
          f"backend={jax.default_backend()}, devices={n}")


class _UniqueNameGenerator:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = {}

    def __call__(self, key):
        with self._lock:
            i = self._count.get(key, 0)
            self._count[key] = i + 1
        return f"{key}_{i}"


_generator = _UniqueNameGenerator()


def generate(key):
    """unique_name.generate parity."""
    return _generator(key)


class unique_name:
    generate = staticmethod(generate)


from . import dlpack  # noqa: E402,F401
from . import download  # noqa: E402,F401
from . import cpp_extension  # noqa: E402,F401
from . import fault_injection  # noqa: E402,F401  (chaos-testing harness)
