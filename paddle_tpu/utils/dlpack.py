"""DLPack interop (ref: python/paddle/utils/dlpack.py:27).

jax arrays speak the DLPack protocol natively; these wrappers adapt the
reference API. Modern consumers (torch.from_dlpack, np.from_dlpack, jax)
exchange protocol OBJECTS (__dlpack__/__dlpack_device__) rather than raw
capsules, so to_dlpack returns a lightweight exporter object implementing
the protocol and from_dlpack accepts any such object (torch tensors, numpy
arrays, other Tensors...)."""
from __future__ import annotations

import jax

from ..tensor_impl import Tensor, as_tensor_data

__all__ = ["to_dlpack", "from_dlpack"]


class _DLPackExporter:
    """Protocol shim: carries the producing array across frameworks."""

    def __init__(self, arr):
        self._arr = arr

    def __dlpack__(self, *args, **kwargs):
        return self._arr.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        return self._arr.__dlpack_device__()


def to_dlpack(x):
    """Tensor -> DLPack exporter (zero-copy where the consumer allows).
    Feed the result to torch.from_dlpack / np.from_dlpack / jax."""
    return _DLPackExporter(as_tensor_data(x))


def from_dlpack(dlpack):
    """DLPack-protocol object (torch tensor, numpy array, exporter from
    to_dlpack, ...) -> Tensor."""
    if not hasattr(dlpack, "__dlpack__"):
        raise TypeError(
            "from_dlpack needs an object implementing the DLPack protocol "
            "(__dlpack__/__dlpack_device__) — pass the producing tensor "
            "itself, e.g. from_dlpack(torch_tensor)")
    arr = jax.dlpack.from_dlpack(dlpack)
    return Tensor(arr)
