"""Weight-file resolution (ref: python/paddle/utils/download.py
get_weights_path_from_url / get_path_from_url).

This deployment is zero-egress: nothing is ever fetched over the network.
A URL resolves to `$PADDLE_TPU_HOME/weights/<basename>` (default
~/.cache/paddle_tpu); pre-populate that directory (or pass an absolute
path) and the pretrained=True machinery picks the file up. A missing file
raises with exact instructions instead of a silent random-init model.
"""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "get_path_from_url", "WEIGHTS_HOME"]


def _home():
    return os.environ.get(
        "PADDLE_TPU_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))


def __getattr__(name):
    # WEIGHTS_HOME tracks PADDLE_TPU_HOME changes at read time
    if name == "WEIGHTS_HOME":
        return os.path.join(_home(), "weights")
    raise AttributeError(name)


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    """Resolve `url` to a local cached path (no network: the file must
    already exist in the cache)."""
    if os.path.isabs(url) and os.path.exists(url):
        return url
    root = root_dir or os.path.join(_home(), "weights")
    fname = os.path.basename(url.split("?")[0]) or "weights.pdparams"
    path = os.path.join(root, fname)
    if check_exist and not os.path.exists(path):
        raise FileNotFoundError(
            f"weight file {fname!r} not found in {root} (zero-egress "
            f"environment: downloads are disabled). Place the file at "
            f"{path} or set PADDLE_TPU_HOME to the cache that contains it.")
    return path


def get_weights_path_from_url(url, md5sum=None):
    """ref: download.py get_weights_path_from_url."""
    return get_path_from_url(url, md5sum=md5sum)
