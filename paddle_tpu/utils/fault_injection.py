"""Deterministic fault injection for the fault-tolerance runtime.

Chaos testing for TPU training: production runs die on NaN steps, torn
checkpoint writes, and preemptions — this module injects exactly those
faults at exact, reproducible points so the recovery machinery
(jit.TrainStep anomaly guard, incubate.checkpoint.CheckpointManager,
distributed.elastic.ElasticAgent) can be tested without flakiness.

Injection sites are pulled, not pushed: the runtime calls the cheap hooks
below at its fault-sensitive points and they no-op unless a ``FaultPlan``
is active (module-level ``_plan`` is None by default, so the cost when
inactive is one attribute check and the compiled step programs are
untouched — batch poisoning happens host-side on the already-materialized
input arrays, never inside an executable).

Faults:
  * ``nan_at_steps``    — poison the floating-point leaves of the batch fed
                          to TrainStep at those step indices (0-based call
                          count) with NaN, which makes loss and grads
                          non-finite inside the compiled step
  * ``io_error_on_writes`` — the nth checkpoint write (1-based) raises
                          ``OSError`` before touching the directory
                          (transient-IO / flaky-NFS simulation)
  * ``preempt_at_step`` — raise ``Preemption`` before dispatching that step
                          (SIGTERM-preemption simulation without signals)

Serving chaos (the self-healing serving ladder):
  * ``kill_at_decode_step`` — raise ``Preemption`` at the START of that
                          serving step boundary (0-based engine step count),
                          BEFORE any snapshot flush — an ABRUPT engine death
                          (vs the SIGTERM drain, which flushes). Fires
                          once; optionally only on the engine whose ``tag``
                          matches ``kill_engine_tag`` (so a supervisor test
                          kills exactly one of N replicas).
  * ``io_error_on_snapshots`` — the nth ENGINE-SNAPSHOT write (1-based,
                          counted only at the ``serving_snapshot`` site)
                          raises OSError, independent of the global
                          ``io_error_on_writes`` schedule.
  * ``stale_heartbeat_ranks`` — those ranks' ``Heartbeat.beat()`` calls are
                          silently dropped (frozen-process simulation): the
                          process looks alive, its heartbeat file goes
                          stale, and the monitor must report it failed.
  * ``chip_loss_at`` /    — deterministic chip/rank-loss schedule for the
    ``chip_return_at``      topology-elastic supervisor: ``{step: ranks}``
                          dicts. ``lost_ranks(step)`` reports the
                          cumulative lost set; the schedule is STICKY
                          across restore rewinds (an internal high-water
                          mark — a supervisor that restores to an earlier
                          step after detecting the loss keeps seeing the
                          rank as lost until a ``chip_return_at`` entry at
                          a step the run has reached re-admits it).
  * ``serving_chip_loss_at`` / ``serving_chip_return_at`` — the SERVING
                          twin of the schedule above, keyed by the serving
                          supervisor's step counter and walked through
                          ``lost_serving_chips(step)`` with its OWN sticky
                          watermark, so serving chaos composes with (and
                          is countable independently of) training chip
                          loss in one plan. Ranks are GLOBAL chip indices
                          into the fleet's device list — losing one chip
                          marks its whole mp group down.
  * ``bitflip_at``        — silent-data-corruption schedule: ``{step:
                          (rank, leaf, bit)}`` flips one MANTISSA bit of a
                          param leaf in exactly ONE dp replica's copy (the
                          value stays finite — invisible to the all-finite
                          guard, caught only by the cross-replica
                          fingerprint under ``FLAGS_sdc_check_every``).
                          One-shot per step, like a real flipped bit.
  * ``kv_bitflip_at`` /   — the serving twins: a finite bit flip in an
    ``corrupt_kv_wire``     engine's KV pool at a serving step (caught by
                          the shadow audit, not the anomaly guard), and
                          1-based page-install indices whose kv_transfer
                          wire payload is corrupted as a COPY with the CRC
                          stamp preserved (refused by the CRC check; the
                          retained clean payload is re-offered).
  * ``surge``             — an ``ArrivalSurge``: a deterministic per-step
                          arrival-count schedule (seeded Poisson base rate
                          with a surge window at a multiplied rate). The
                          traffic driver polls ``surge_arrivals(step)`` at
                          each boundary and submits that many requests —
                          reproducible overload for the SLO chaos ladder
                          (shed/recover, upgrade-under-load, kill-during-
                          surge) without wall-clock flakiness.

All hooks are host-side and zero-cost when no plan is active (one
attribute check), and never touch a compiled executable.
"""
from __future__ import annotations

import numpy as np


class Preemption(BaseException):
    """Simulated preemption. Derives from BaseException so ordinary
    ``except Exception`` recovery paths (e.g. ElasticAgent's restart loop)
    do not swallow it — a preempted process must save and exit, not
    retrain."""


class ArrivalSurge:
    """Deterministic arrival-count schedule for serving chaos: a seeded
    Poisson stream at ``base_rate`` arrivals/step, multiplied to
    ``surge_rate`` over ``[surge_start, surge_start + surge_steps)``. The
    whole schedule is materialized once from the seed, so two runs of the
    same ladder see IDENTICAL traffic step for step — surges are
    reproducible, never wall-clock-dependent. Host-side only; the plan
    hook ``surge_arrivals`` costs one attribute check when inactive."""

    def __init__(self, base_rate=0.5, surge_rate=4.0, surge_start=8,
                 surge_steps=16, total_steps=256, seed=0):
        self.base_rate = float(base_rate)
        self.surge_rate = float(surge_rate)
        self.surge_start = int(surge_start)
        self.surge_steps = int(surge_steps)
        self.total_steps = int(total_steps)
        self.seed = int(seed)
        rng = np.random.default_rng(self.seed)
        rates = np.full(self.total_steps, self.base_rate)
        rates[self.surge_start:self.surge_start + self.surge_steps] = \
            self.surge_rate
        self.counts = rng.poisson(rates).astype(np.int64)

    def arrivals(self, step):
        """Arrival count at ``step`` (0 past the schedule's end)."""
        step = int(step)
        if 0 <= step < self.total_steps:
            return int(self.counts[step])
        return 0

    def in_surge(self, step):
        return self.surge_start <= int(step) < \
            self.surge_start + self.surge_steps

    def __repr__(self):
        return (f"ArrivalSurge(base_rate={self.base_rate}, "
                f"surge_rate={self.surge_rate}, "
                f"surge_start={self.surge_start}, "
                f"surge_steps={self.surge_steps}, "
                f"total_steps={self.total_steps}, seed={self.seed})")


# single source of truth for the stat keys: FaultPlan.__init__ and the
# no-active-plan stats() both copy it, so a new counter can never exist
# in one and not the other
_ZERO_STATS = {"poisoned_steps": 0, "io_errors": 0, "preemptions": 0,
               "writes_seen": 0, "serving_kills": 0,
               "snapshot_writes_seen": 0, "snapshot_io_errors": 0,
               "heartbeats_dropped": 0, "surged_arrivals": 0,
               "chip_losses": 0, "chip_returns": 0,
               "serving_chip_losses": 0, "serving_chip_returns": 0,
               "bitflips": 0, "kv_bitflips": 0, "kv_wire_corruptions": 0}


class FaultPlan:
    """Deterministic schedule of injected faults."""

    def __init__(self, nan_at_steps=(), io_error_on_writes=(),
                 preempt_at_step=None, kill_at_decode_step=None,
                 kill_engine_tag=None, io_error_on_snapshots=(),
                 stale_heartbeat_ranks=(), surge=None,
                 chip_loss_at=None, chip_return_at=None,
                 serving_chip_loss_at=None, serving_chip_return_at=None,
                 bitflip_at=None, kv_bitflip_at=None,
                 kv_bitflip_engine_tag=None, corrupt_kv_wire=()):
        self.nan_at_steps = frozenset(int(s) for s in nan_at_steps)
        self.io_error_on_writes = frozenset(int(n) for n in io_error_on_writes)
        self.preempt_at_step = (None if preempt_at_step is None
                                else int(preempt_at_step))
        # serving chaos
        self.kill_at_decode_step = (None if kill_at_decode_step is None
                                    else int(kill_at_decode_step))
        self.kill_engine_tag = kill_engine_tag
        self.io_error_on_snapshots = frozenset(
            int(n) for n in io_error_on_snapshots)
        self.stale_heartbeat_ranks = frozenset(
            int(r) for r in stale_heartbeat_ranks)
        self.surge = surge

        def _ranks_by_step(sched):
            out = {}
            for s, ranks in (sched or {}).items():
                if isinstance(ranks, (int, np.integer)):
                    ranks = (ranks,)
                out[int(s)] = frozenset(int(r) for r in ranks)
            return out

        self.chip_loss_at = _ranks_by_step(chip_loss_at)
        self.chip_return_at = _ranks_by_step(chip_return_at)
        self.serving_chip_loss_at = _ranks_by_step(serving_chip_loss_at)
        self.serving_chip_return_at = _ranks_by_step(serving_chip_return_at)

        def _flips_by_step(sched, width):
            # {step: entry | [entries]} -> {step: (entry, ...)}; each entry
            # is padded with a default mantissa bit (a SILENT flip — the
            # value stays finite, invisible to the all-finite guard)
            out = {}
            for s, entries in (sched or {}).items():
                if entries and not isinstance(entries[0], (tuple, list)):
                    entries = (entries,)
                norm = []
                for e in entries:
                    e = tuple(e)
                    if len(e) == width - 1:
                        e = e + (12,)          # default: mantissa bit 12
                    norm.append(e)
                out[int(s)] = tuple(norm)
            return out

        # {step: (rank, leaf_name, bit)} — flip one bit of element 0 of
        # that param leaf in exactly ONE dp replica's copy
        self.bitflip_at = _flips_by_step(bitflip_at, 3)
        # {step: (page, layer, bit)} — flip one bit of a KV-pool page in
        # the engine that polls at that serving step
        self.kv_bitflip_at = _flips_by_step(kv_bitflip_at, 3)
        self.kv_bitflip_engine_tag = kv_bitflip_engine_tag
        # 1-based page-install indices whose wire payload is corrupted (a
        # COPY is corrupted at install time; the sender's retained payload
        # stays clean, so a CRC refusal can re-offer it)
        self.corrupt_kv_wire = frozenset(int(n) for n in corrupt_kv_wire)
        self._kv_wire_seen = 0
        # one-shot: re-walking a step after a repair/restore must not
        # re-corrupt (the physical flip happened once)
        self._bitflips_fired = set()
        self._kv_bitflips_fired = set()
        # high-water marks of steps each run has REACHED: a restore that
        # rewinds the step counter must keep already-fired losses visible.
        # Training and serving walk SEPARATE watermarks — their step
        # counters tick independently.
        self._chip_watermark = -1
        self._serving_chip_watermark = -1
        # one-shot: a respawned/replayed engine re-walks the same step
        # indices — re-firing the kill would loop the recovery forever
        self._kill_fired = False
        # observability: what actually fired
        self.stats = dict(_ZERO_STATS)

    def __repr__(self):
        return (f"FaultPlan(nan_at_steps={sorted(self.nan_at_steps)}, "
                f"io_error_on_writes={sorted(self.io_error_on_writes)}, "
                f"preempt_at_step={self.preempt_at_step}, "
                f"kill_at_decode_step={self.kill_at_decode_step}, "
                f"kill_engine_tag={self.kill_engine_tag!r}, "
                f"io_error_on_snapshots={sorted(self.io_error_on_snapshots)}, "
                f"stale_heartbeat_ranks={sorted(self.stale_heartbeat_ranks)}, "
                f"surge={self.surge!r}, "
                f"chip_loss_at={dict(sorted((k, sorted(v)) for k, v in self.chip_loss_at.items()))}, "
                f"chip_return_at={dict(sorted((k, sorted(v)) for k, v in self.chip_return_at.items()))}, "
                f"serving_chip_loss_at={dict(sorted((k, sorted(v)) for k, v in self.serving_chip_loss_at.items()))}, "
                f"serving_chip_return_at={dict(sorted((k, sorted(v)) for k, v in self.serving_chip_return_at.items()))}, "
                f"bitflip_at={dict(sorted(self.bitflip_at.items()))}, "
                f"kv_bitflip_at={dict(sorted(self.kv_bitflip_at.items()))}, "
                f"corrupt_kv_wire={sorted(self.corrupt_kv_wire)})")


_plan: FaultPlan | None = None
_last_plan: FaultPlan | None = None


def activate(plan: FaultPlan):
    """Install ``plan`` globally; returns it for chaining."""
    global _plan, _last_plan
    _plan = _last_plan = plan
    return plan


def deactivate():
    global _plan
    _plan = None


def active():
    return _plan


class inject:
    """Context manager form: ``with fault_injection.inject(plan): ...``"""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self):
        activate(self.plan)
        return self.plan

    def __exit__(self, *exc):
        deactivate()


# -- hooks consulted by the runtime ------------------------------------------


def maybe_poison(step, *trees):
    """Return ``trees`` with every inexact-float array replaced by NaN when
    the active plan poisons ``step``; the original objects otherwise
    (bitwise no-op when inactive — same array identities)."""
    if _plan is None or int(step) not in _plan.nan_at_steps:
        return trees if len(trees) != 1 else trees[0]
    _plan.stats["poisoned_steps"] += 1

    def poison(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full(arr.shape, np.nan, arr.dtype)
        return x

    import jax
    out = tuple(jax.tree_util.tree_map(poison, t) for t in trees)
    return out if len(out) != 1 else out[0]


def maybe_preempt(step):
    """Raise ``Preemption`` when the active plan preempts at ``step``."""
    if _plan is not None and _plan.preempt_at_step == int(step):
        _plan.stats["preemptions"] += 1
        raise Preemption(f"simulated preemption at step {step}")


def maybe_fail_write(site="ckpt_write"):
    """Called by CheckpointManager before each on-disk write attempt; the
    nth call (1-based, counted across all managers) raises OSError when the
    plan schedules it. Serving-snapshot managers call with
    ``site="serving_snapshot"``, which additionally walks the separate
    ``io_error_on_snapshots`` schedule (so snapshot chaos composes with —
    and is countable independently of — training checkpoint chaos)."""
    if _plan is None:
        return
    _plan.stats["writes_seen"] += 1
    if _plan.stats["writes_seen"] in _plan.io_error_on_writes:
        _plan.stats["io_errors"] += 1
        raise OSError(
            f"injected I/O error on checkpoint write "
            f"#{_plan.stats['writes_seen']} ({site})")
    if site == "serving_snapshot":
        _plan.stats["snapshot_writes_seen"] += 1
        if _plan.stats["snapshot_writes_seen"] in _plan.io_error_on_snapshots:
            _plan.stats["snapshot_io_errors"] += 1
            raise OSError(
                f"injected I/O error on engine snapshot write "
                f"#{_plan.stats['snapshot_writes_seen']}")


def maybe_kill_serving(tag, decode_step):
    """Called by Engine.step() at every boundary: raises ``Preemption`` the
    first time the plan's ``kill_at_decode_step`` is reached by an engine
    whose tag matches (or by any engine when ``kill_engine_tag`` is None).
    Abrupt by design — nothing is flushed; recovery must come from the
    last periodic snapshot or from request replay."""
    if _plan is None or _plan.kill_at_decode_step is None \
            or _plan._kill_fired:
        return
    if _plan.kill_engine_tag is not None and tag != _plan.kill_engine_tag:
        return
    if int(decode_step) >= _plan.kill_at_decode_step:
        _plan._kill_fired = True
        _plan.stats["serving_kills"] += 1
        raise Preemption(
            f"simulated engine kill ({tag}) at decode step {decode_step}")


def surge_arrivals(step):
    """Arrival count the active plan's surge schedules at ``step`` (0 when
    no plan / no surge is active). Traffic drivers (the SLO chaos ladder,
    load tests) poll this at every step boundary and submit that many
    requests — deterministic overload, zero cost when inactive."""
    if _plan is None or _plan.surge is None:
        return 0
    n = _plan.surge.arrivals(step)
    _plan.stats["surged_arrivals"] += n
    return n


def _walk_chip_schedule(step, loss_at, return_at, wm_attr, stat_prefix):
    """Shared sticky-watermark walk of a chip loss/return schedule: apply
    entries in step order up to the HIGHEST step ever queried, so a
    restore that rewinds the step counter keeps already-fired losses
    visible, exactly like a real dead chip."""
    wm = getattr(_plan, wm_attr)
    step = int(step)
    if step > wm:
        for s in range(wm + 1, step + 1):
            _plan.stats[f"{stat_prefix}_losses"] += len(loss_at.get(s, ()))
            _plan.stats[f"{stat_prefix}_returns"] += len(
                return_at.get(s, ()))
        setattr(_plan, wm_attr, step)
        wm = step
    lost = set()
    for s in sorted(set(loss_at) | set(return_at)):
        if s > wm:
            break
        lost |= loss_at.get(s, frozenset())
        lost -= return_at.get(s, frozenset())
    return frozenset(lost)


def lost_ranks(step):
    """Cumulative set of lost (and not yet returned) ranks as of ``step``
    under the active plan's chip-loss schedule — the injected-device-
    failure signal the topology-elastic supervisor polls at every step
    boundary. The schedule is applied in step order up to the HIGHEST
    step ever queried (sticky watermark): a supervisor that detects the
    loss, restores an older snapshot and re-walks earlier step indices
    keeps seeing the rank as lost, exactly like a real dead chip.
    Zero-cost inactive (one attribute check); returns a frozenset."""
    if _plan is None or not (_plan.chip_loss_at or _plan.chip_return_at):
        return frozenset()
    return _walk_chip_schedule(step, _plan.chip_loss_at,
                               _plan.chip_return_at, "_chip_watermark",
                               "chip")


def lost_serving_chips(step):
    """Serving-scoped twin of ``lost_ranks``: the cumulative lost chip set
    as of the serving supervisor's step ``step`` under the plan's
    ``serving_chip_loss_at``/``serving_chip_return_at`` schedule, with its
    own sticky watermark (the serving and training step counters tick
    independently). Ranks are global chip indices into the serving
    fleet's device list. Zero-cost inactive; returns a frozenset."""
    if _plan is None or not (_plan.serving_chip_loss_at
                             or _plan.serving_chip_return_at):
        return frozenset()
    return _walk_chip_schedule(step, _plan.serving_chip_loss_at,
                               _plan.serving_chip_return_at,
                               "_serving_chip_watermark", "serving_chip")


def maybe_drop_heartbeat(rank):
    """Called by ``Heartbeat.beat()``: True when the plan freezes this
    rank's heartbeats (the beat is silently skipped, the file goes stale)."""
    if _plan is None or int(rank) not in _plan.stale_heartbeat_ranks:
        return False
    _plan.stats["heartbeats_dropped"] += 1
    return True


def param_bitflips(step):
    """Silent-data-corruption schedule for training: the ``(rank, leaf,
    bit)`` entries the active plan flips at ``step``, fired ONCE per step
    (a repair/restore that re-walks the step must not re-corrupt — the
    physical flip happened once). The caller (jit.TrainStep under
    ``FLAGS_sdc_check_every``) applies each entry to exactly one dp
    replica's copy of the named param leaf via
    ``distributed.integrity.inject_bitflips``. Zero-cost inactive;
    returns a tuple."""
    if _plan is None or not _plan.bitflip_at:
        return ()
    step = int(step)
    if step in _plan._bitflips_fired:
        return ()
    entries = _plan.bitflip_at.get(step, ())
    if entries:
        _plan._bitflips_fired.add(step)
        _plan.stats["bitflips"] += len(entries)
    return entries


def maybe_kv_bitflip(tag, step):
    """Serving twin: the ``(page, layer, bit)`` entries to flip in the
    KV pool of the engine whose tag matches (any engine when
    ``kv_bitflip_engine_tag`` is None) at serving step ``step`` —
    one-shot per step. The flip stays FINITE (mantissa bit), so the
    all-finite anomaly guard cannot see it; only the shadow audit can.
    Zero-cost inactive; returns a tuple."""
    if _plan is None or not _plan.kv_bitflip_at:
        return ()
    if _plan.kv_bitflip_engine_tag is not None \
            and tag != _plan.kv_bitflip_engine_tag:
        return ()
    step = int(step)
    if step in _plan._kv_bitflips_fired:
        return ()
    entries = _plan.kv_bitflip_at.get(step, ())
    if entries:
        _plan._kv_bitflips_fired.add(step)
        _plan.stats["kv_bitflips"] += len(entries)
    return entries


def maybe_corrupt_kv_payload(payload):
    """Wire-corruption hook, called by the decode engine for each page
    payload at INSTALL time: the nth install (1-based, across engines)
    scheduled in ``corrupt_kv_wire`` returns a corrupted COPY — one bit
    flipped in the page bytes, the original CRC stamp preserved — so a
    CRC verify must refuse it while the sender's retained payload stays
    clean for the re-offer. Returns ``payload`` unchanged otherwise
    (same object identity; zero-cost inactive)."""
    if _plan is None or not _plan.corrupt_kv_wire:
        return payload
    _plan._kv_wire_seen += 1
    if _plan._kv_wire_seen not in _plan.corrupt_kv_wire:
        return payload
    _plan.stats["kv_wire_corruptions"] += 1
    from ..serving.kv_transfer import PagePayload
    k = payload.k.copy()
    k.view(np.uint8).reshape(-1)[0] ^= 0x10
    return PagePayload(payload.index, k, payload.v,
                       payload.k_scale, payload.v_scale, crc=payload.crc)


def stats():
    """Stats of the active (or last active) plan; zeros when never active."""
    plan = _plan or _last_plan
    if plan is None:
        return dict(_ZERO_STATS)
    return dict(plan.stats)
