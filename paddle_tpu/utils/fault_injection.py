"""Deterministic fault injection for the fault-tolerance runtime.

Chaos testing for TPU training: production runs die on NaN steps, torn
checkpoint writes, and preemptions — this module injects exactly those
faults at exact, reproducible points so the recovery machinery
(jit.TrainStep anomaly guard, incubate.checkpoint.CheckpointManager,
distributed.elastic.ElasticAgent) can be tested without flakiness.

Injection sites are pulled, not pushed: the runtime calls the cheap hooks
below at its fault-sensitive points and they no-op unless a ``FaultPlan``
is active (module-level ``_plan`` is None by default, so the cost when
inactive is one attribute check and the compiled step programs are
untouched — batch poisoning happens host-side on the already-materialized
input arrays, never inside an executable).

Faults:
  * ``nan_at_steps``    — poison the floating-point leaves of the batch fed
                          to TrainStep at those step indices (0-based call
                          count) with NaN, which makes loss and grads
                          non-finite inside the compiled step
  * ``io_error_on_writes`` — the nth checkpoint write (1-based) raises
                          ``OSError`` before touching the directory
                          (transient-IO / flaky-NFS simulation)
  * ``preempt_at_step`` — raise ``Preemption`` before dispatching that step
                          (SIGTERM-preemption simulation without signals)
"""
from __future__ import annotations

import numpy as np


class Preemption(BaseException):
    """Simulated preemption. Derives from BaseException so ordinary
    ``except Exception`` recovery paths (e.g. ElasticAgent's restart loop)
    do not swallow it — a preempted process must save and exit, not
    retrain."""


class FaultPlan:
    """Deterministic schedule of injected faults."""

    def __init__(self, nan_at_steps=(), io_error_on_writes=(),
                 preempt_at_step=None):
        self.nan_at_steps = frozenset(int(s) for s in nan_at_steps)
        self.io_error_on_writes = frozenset(int(n) for n in io_error_on_writes)
        self.preempt_at_step = (None if preempt_at_step is None
                                else int(preempt_at_step))
        # observability: what actually fired
        self.stats = {"poisoned_steps": 0, "io_errors": 0, "preemptions": 0,
                      "writes_seen": 0}

    def __repr__(self):
        return (f"FaultPlan(nan_at_steps={sorted(self.nan_at_steps)}, "
                f"io_error_on_writes={sorted(self.io_error_on_writes)}, "
                f"preempt_at_step={self.preempt_at_step})")


_plan: FaultPlan | None = None
_last_plan: FaultPlan | None = None


def activate(plan: FaultPlan):
    """Install ``plan`` globally; returns it for chaining."""
    global _plan, _last_plan
    _plan = _last_plan = plan
    return plan


def deactivate():
    global _plan
    _plan = None


def active():
    return _plan


class inject:
    """Context manager form: ``with fault_injection.inject(plan): ...``"""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self):
        activate(self.plan)
        return self.plan

    def __exit__(self, *exc):
        deactivate()


# -- hooks consulted by the runtime ------------------------------------------


def maybe_poison(step, *trees):
    """Return ``trees`` with every inexact-float array replaced by NaN when
    the active plan poisons ``step``; the original objects otherwise
    (bitwise no-op when inactive — same array identities)."""
    if _plan is None or int(step) not in _plan.nan_at_steps:
        return trees if len(trees) != 1 else trees[0]
    _plan.stats["poisoned_steps"] += 1

    def poison(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full(arr.shape, np.nan, arr.dtype)
        return x

    import jax
    out = tuple(jax.tree_util.tree_map(poison, t) for t in trees)
    return out if len(out) != 1 else out[0]


def maybe_preempt(step):
    """Raise ``Preemption`` when the active plan preempts at ``step``."""
    if _plan is not None and _plan.preempt_at_step == int(step):
        _plan.stats["preemptions"] += 1
        raise Preemption(f"simulated preemption at step {step}")


def maybe_fail_write(site="ckpt_write"):
    """Called by CheckpointManager before each on-disk write attempt; the
    nth call (1-based, counted across all managers) raises OSError when the
    plan schedules it."""
    if _plan is None:
        return
    _plan.stats["writes_seen"] += 1
    if _plan.stats["writes_seen"] in _plan.io_error_on_writes:
        _plan.stats["io_errors"] += 1
        raise OSError(
            f"injected I/O error on checkpoint write "
            f"#{_plan.stats['writes_seen']} ({site})")


def stats():
    """Stats of the active (or last active) plan; zeros when never active."""
    plan = _plan or _last_plan
    if plan is None:
        return {"poisoned_steps": 0, "io_errors": 0, "preemptions": 0,
                "writes_seen": 0}
    return dict(plan.stats)
