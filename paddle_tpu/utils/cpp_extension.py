"""JIT C++ extension loading (ref: python/paddle/utils/cpp_extension/
cpp_extension.py:79 setup(), extension_utils.py _jit_compile / load()).

The reference compiles user C++/CUDA operator sources against the paddle
runtime and registers the results as framework operators. In the TPU-native
stack, DEVICE custom ops are pallas/jax kernels registered via
`paddle_tpu.ops.custom.register_custom_op` (no compilation step — see that
module). This module keeps the literal C++ path for HOST-side ops — data
loaders, tokenizers, CPU pre/post-processing — the same role the repo's own
`native/dataio.cpp` plays: `load()` compiles the sources with g++ into a
shared object and returns a ctypes handle.

Example::

    lib = load(name="my_ops", sources=["my_ops.cc"])   # g++ -O3 -shared
    lib.my_kernel.restype = None
    lib.my_kernel.argtypes = [...]

Functions are plain `extern "C"` symbols operating on raw buffers (pass
numpy arrays via ctypes; zero-copy through ndarray.ctypes).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile


class BuildError(RuntimeError):
    pass


DEFAULT_CXX_FLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared"]


def load(name, sources, extra_cxx_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None, verbose=False):
    """Compile C++ `sources` into `lib{name}.so` and return the ctypes CDLL
    (ref: cpp_extension load()). Re-links only when sources are newer than
    the cached object."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    out_path = os.path.join(build_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    for s in srcs:
        if not os.path.exists(s):
            raise FileNotFoundError(s)
    if (not os.path.exists(out_path)
            or any(os.path.getmtime(s) > os.path.getmtime(out_path)
                   for s in srcs)):
        cmd = ["g++", *DEFAULT_CXX_FLAGS]
        for inc in (extra_include_paths or []):
            cmd += ["-I", inc]
        cmd += (extra_cxx_cflags or [])
        cmd += srcs
        cmd += ["-o", out_path]
        cmd += (extra_ldflags or [])
        if verbose:
            print("cpp_extension:", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise BuildError(
                f"g++ failed (rc={proc.returncode}):\n{proc.stderr[-4000:]}")
    return ctypes.CDLL(out_path)


class CppExtension:
    """Descriptor for setup()-style builds (ref: CppExtension). Thin data
    holder: `setup` compiles each extension eagerly via `load`."""

    def __init__(self, sources, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):  # noqa: N802 — reference-parity name
    raise NotImplementedError(
        "CUDA custom ops do not exist on TPU. Device custom kernels are "
        "pallas/jax functions — register them with "
        "paddle_tpu.ops.custom.register_custom_op (no compilation step).")


def setup(name="", ext_modules=None, **kwargs):
    """Compile every CppExtension now and return {ext_name: CDLL}
    (ref: cpp_extension.py:79 setup). The reference installs an importable
    python module; here the compiled host library handles are returned
    directly (and cached on disk), which fits the single-process TPU
    runtime."""
    exts = ext_modules or []
    if not isinstance(exts, (list, tuple)):
        exts = [exts]
    out = {}
    for i, ext in enumerate(exts):
        if isinstance(ext, CppExtension):
            ext_name = ext.kwargs.get("name", f"{name}_{i}" if name else
                                      f"ext_{i}")
            out[ext_name] = load(ext_name, ext.sources,
                                 **{k: v for k, v in ext.kwargs.items()
                                    if k != "name"})
        else:
            raise TypeError(f"unsupported extension type {type(ext)}")
    return out
