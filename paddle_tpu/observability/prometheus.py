"""Prometheus text exposition over the metrics registry.

Pull-based: ``start_metrics_server(port)`` runs a stdlib ``http.server``
in a daemon thread serving ``GET /metrics`` with the registry snapshot in
text exposition format (version 0.0.4). Default OFF — the server starts
only when asked, or via ``start_from_flags()`` when ``FLAGS_metrics_port``
is non-zero. Rendering walks ``REGISTRY.snapshot()``: numeric entries
become ``paddle_tpu_<family>_<metric>`` gauges, non-numeric entries
(backend labels, finish reasons) are skipped. ``port=0`` binds an
ephemeral port (tests; read it back from ``server.port``).
"""
from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(key):
    name = "paddle_tpu_" + _NAME_RE.sub("_", str(key))
    if name[len("paddle_tpu_")].isdigit():
        name = "paddle_tpu__" + name[len("paddle_tpu_"):]
    return name


def render(snapshot=None):
    """Registry snapshot -> Prometheus text exposition (one gauge per
    numeric entry; inf/nan rendered per the exposition spec)."""
    if snapshot is None:
        from .registry import REGISTRY
        snapshot = REGISTRY.snapshot()
    lines = []
    for key in sorted(snapshot):
        v = snapshot[key]
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            continue
        name = _metric_name(key)
        lines.append(f"# TYPE {name} gauge")
        if v != v:                       # NaN
            val = "NaN"
        elif v in (float("inf"), float("-inf")):
            val = "+Inf" if v > 0 else "-Inf"
        else:
            val = repr(float(v)) if isinstance(v, float) else str(v)
        lines.append(f"{name} {val}")
    return "\n".join(lines) + "\n"


def parse(text):
    """Parse a text exposition page back to {name: float} — the smoke
    tool's "the page actually parses" gate (comment/TYPE lines skipped,
    malformed lines raise)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2 or not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*",
                                               parts[0]):
            raise ValueError(f"malformed exposition line: {line!r}")
        out[parts[0]] = float(parts[1])
    return out


class _Handler(BaseHTTPRequestHandler):
    # a half-open scraper connection must neither wedge the endpoint
    # (ThreadingHTTPServer below serves concurrently) nor leak its
    # handler thread forever (read timeout)
    timeout = 10

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        try:
            body = render().encode()
        except Exception as e:  # noqa: BLE001 — scrape must not kill server
            self.send_error(500, repr(e))
            return
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # silence per-scrape stderr noise
        pass


class MetricsServer:
    """Daemon-thread HTTP server exposing /metrics. ``port=0`` binds an
    ephemeral port (read ``server.port``)."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="paddle-tpu-metrics",
            daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_server = None
_server_lock = threading.Lock()


def start_metrics_server(port=0, host="127.0.0.1"):
    """Start (or return the already-running) metrics endpoint."""
    global _server
    with _server_lock:
        if _server is None:
            _server = MetricsServer(port, host)
        return _server


def stop_metrics_server():
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def start_from_flags():
    """Honor FLAGS_metrics_port: start the endpoint when non-zero, else
    return None (the default-off contract). Called from Engine/TrainStep
    construction, so a bind failure (port taken by a sibling process)
    degrades to a warning — telemetry must never kill the job."""
    from ..flags import _FLAGS
    port = int(_FLAGS.get("FLAGS_metrics_port", 0) or 0)
    if port <= 0:
        return None
    try:
        return start_metrics_server(port)
    except OSError as e:
        import warnings
        warnings.warn(f"FLAGS_metrics_port={port}: metrics endpoint not "
                      f"started ({e}); set a free port or use "
                      f"start_metrics_server(0) for an ephemeral one")
        return None
