"""Unified telemetry for paddle_tpu (ROADMAP: the live instrument layer).

One registry, three signal kinds, two exports:

* **registry** (registry.py) — typed counters/gauges/histograms with
  namespaced keys and snapshot/delta semantics; the six pre-existing
  counter families (dispatch / comm / mp_comm / fault / serving /
  recovery) register as lazy collectors, and ``profiler.*_counters()``
  are thin views over them.
* **span tracing** (tracing.py) — per-request serving spans
  (queue → prefill chunks → decode → deliver, plus CoW/prefix and
  self-healing hops), survivable through engine snapshots, exported as
  Perfetto/Chrome-trace JSON or a JSONL sink. ``FLAGS_serving_trace``.
* **step telemetry** (step_telemetry.py) — sampled live training-step
  records (dispatch/sync wall split, achieved MFU from the static FLOP
  estimator in flops.py — the same one bench.py uses — wire bytes from
  the static comm schedules, memory watermarks) with an EWMA step-time
  regression sentinel. ``FLAGS_step_telemetry``.
* **Prometheus** (prometheus.py) — pull-based /metrics text exposition
  over the registry snapshot (``FLAGS_metrics_port``, default off).

Everything is host-side: no traced operands, no retraces, and when the
flags are off the cost is one dict lookup per step / per request.
"""
from __future__ import annotations

from .registry import REGISTRY, Counter, Gauge, Histogram, MetricsRegistry
from .families import register_default_families, register_supervisor
from .tracing import (
    JsonlTraceSink, RequestTrace, add_sink, chrome_events, export_perfetto,
    remove_sink, traces,
)
from .step_telemetry import (
    StepSampler, default_peak_flops, reset_step_telemetry, step_counters,
    step_summary,
)
from .flops import (
    dense_flops_per_token, mfu, model_flops_per_token, peak_flops_bf16,
    train_step_flops,
)
from .prometheus import (
    MetricsServer, render, start_from_flags, start_metrics_server,
    stop_metrics_server,
)

register_default_families()


def collect(family):
    """Current dict of one counter family (the profiler thin-view hook)."""
    return REGISTRY.collect(family)


def snapshot():
    """Flat {"family.metric": value} snapshot of everything."""
    return REGISTRY.snapshot()


def delta(prev, cur=None):
    """Numeric difference between two snapshots."""
    return REGISTRY.delta(prev, cur)


__all__ = [
    "REGISTRY", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "register_default_families", "register_supervisor",
    "RequestTrace", "JsonlTraceSink", "add_sink", "remove_sink",
    "chrome_events", "export_perfetto", "traces",
    "StepSampler", "default_peak_flops", "reset_step_telemetry",
    "step_counters", "step_summary",
    "model_flops_per_token", "dense_flops_per_token", "train_step_flops",
    "peak_flops_bf16", "mfu",
    "MetricsServer", "render", "start_metrics_server",
    "stop_metrics_server", "start_from_flags",
    "collect", "snapshot", "delta",
]
