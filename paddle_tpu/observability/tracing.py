"""Per-request span tracing for the serving stack.

Every ``Request`` served with ``FLAGS_serving_trace`` on carries a
``RequestTrace``: an append-only list of spans recorded host-side at the
points the engine already timestamps anyway — queue wait (submit→admit),
each prefill chunk, each decode step, CoW/prefix-cache events, and the
self-healing hops (requeue / replay / snapshot-restore). Span timestamps
REUSE the exact ``perf_counter`` values the SLO ledger records
(``submit_t`` / ``first_token_t`` / ``finish_t``), so an exported trace
reconciles with the request's recorded TTFT and latency to the float —
"why was THIS request's TTFT 900ms" is answered by reading its spans.

Traces survive engine snapshots: ``RequestTrace.to_state()`` rides in
``Request.to_state()``, and ``Engine.load_state_dict`` shifts the spans
with the same clock re-anchoring it applies to the request timestamps —
a kill-and-resume request's trace shows the pre-kill spans, the restore
hop, and the post-restore spans on one consistent timeline.

Finished traces land in a bounded module ring (``collect``) and export as
Perfetto-loadable Chrome-trace JSON (``export_perfetto``) or stream to a
structured JSONL sink (``add_sink`` / ``JsonlTraceSink``). Everything is
host-side: tracing on/off never changes a compiled executable, a traced
operand, or a trace counter.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque


class RequestTrace:
    """Append-only span list for one request. A span is a dict
    ``{"name", "t0", "t1", ...meta}`` with perf_counter-domain seconds;
    ``t1 == t0`` marks an instant event."""

    __slots__ = ("request_id", "spans")

    def __init__(self, request_id, spans=None):
        self.request_id = int(request_id)
        self.spans = list(spans or ())

    def span(self, name, t0, t1, **meta):
        ev = {"name": name, "t0": float(t0), "t1": float(t1)}
        if meta:
            ev.update(meta)
        self.spans.append(ev)
        return ev

    def instant(self, name, t=None, **meta):
        t = time.perf_counter() if t is None else t
        return self.span(name, t, t, **meta)

    def tail(self):
        """Latest span end, or None — where a post-requeue queue span
        starts so hops never overlap the pre-drain timeline."""
        return max((ev["t1"] for ev in self.spans), default=None)

    def shift(self, dt):
        """Re-anchor every span onto a new clock origin (the engine-restore
        companion of the request-timestamp shift)."""
        for ev in self.spans:
            ev["t0"] += dt
            ev["t1"] += dt

    def duration_sum(self, names=None):
        return sum(ev["t1"] - ev["t0"] for ev in self.spans
                   if names is None or ev["name"] in names)

    # -- snapshot ------------------------------------------------------------
    def to_state(self):
        return [dict(ev) for ev in self.spans]

    @classmethod
    def from_state(cls, request_id, spans):
        return cls(request_id, [dict(ev) for ev in spans or ()])

    def copy(self):
        return RequestTrace.from_state(self.request_id, self.spans)


# -- finished-trace collection ------------------------------------------------

_lock = threading.Lock()
_done = deque(maxlen=4096)
_seen = set()        # request_ids currently in the ring: first-wins dedup
_sinks = []


def _maxlen():
    from ..flags import _FLAGS
    return int(_FLAGS.get("FLAGS_trace_buffer", 4096) or 4096)


def collect(req, engine_tag="engine"):
    """Archive a resolved request's trace (called by ``Engine._resolve``;
    no-op when the request is untraced). The record is self-contained —
    the SLO numbers ride along so sinks and exports never need the
    Request back.

    First result wins per request_id (mirroring the supervisor's delivery
    dedup): a snapshot-respawned replica recomputing already-archived
    work, or a hygiene-cancel of a stale snapshot copy, does not mint a
    duplicate timeline. The dedup window is the RETAINED ring
    (FLAGS_trace_buffer): once a record is evicted its id is forgotten —
    a bounded set, not a forever-growing one — so a recompute arriving
    thousands of requests later can re-archive; downstream consumers that
    join on request_id should keep the first record they saw."""
    trace = getattr(req, "trace", None)
    if trace is None:
        return None
    rec = {
        "request_id": int(req.request_id),
        "engine": str(engine_tag),
        "finish_reason": req.finish_reason,
        "requeue_count": int(getattr(req, "requeue_count", 0)),
        "ttft": (None if req.first_token_t is None or req.submit_t is None
                 else req.first_token_t - req.submit_t),
        "latency": (None if req.finish_t is None or req.submit_t is None
                    else req.finish_t - req.submit_t),
        "tokens": len(req.tokens),
        "spans": trace.to_state(),
    }
    with _lock:
        global _done
        if rec["request_id"] in _seen:
            return None
        ml = _maxlen()
        if _done.maxlen != ml:                    # FLAGS_trace_buffer moved
            kept = list(_done)[max(0, len(_done) - ml):]
            _done = deque(kept, maxlen=ml)
            _seen.intersection_update(r["request_id"] for r in kept)
        if len(_done) == _done.maxlen:
            # evict explicitly so the dedup set tracks the ring (deque
            # maxlen would evict silently); O(1) at steady state
            _seen.discard(_done.popleft()["request_id"])
        _done.append(rec)
        _seen.add(rec["request_id"])
        sinks = list(_sinks)
    for sink in sinks:
        try:
            sink(rec)
        except Exception:  # noqa: BLE001 — a broken sink must not
            pass           # unwind the serving step
    from .registry import REGISTRY
    REGISTRY.counter("serving.trace.requests").inc()
    REGISTRY.counter("serving.trace.spans").inc(len(rec["spans"]))
    return rec


def traces():
    """Snapshot of the collected finished-request traces (newest last)."""
    with _lock:
        return [dict(r, spans=[dict(s) for s in r["spans"]]) for r in _done]


def clear():
    with _lock:
        _done.clear()
        _seen.clear()


def add_sink(fn):
    """Register a callable invoked with each finished trace record."""
    with _lock:
        _sinks.append(fn)
    return fn


def remove_sink(fn):
    with _lock:
        try:
            _sinks.remove(fn)
        except ValueError:
            pass


class JsonlTraceSink:
    """Structured JSONL sink: one line per finished request. Register with
    ``add_sink(JsonlTraceSink(path))``; ``close()`` removes + flushes."""

    def __init__(self, path):
        self.path = path
        self._f = open(path, "a")
        self._lock = threading.Lock()
        add_sink(self)

    def __call__(self, rec):
        line = json.dumps(rec)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self):
        remove_sink(self)
        with self._lock:
            self._f.close()


# -- Perfetto / Chrome-trace export -------------------------------------------

def chrome_events(records=None):
    """Chrome-trace event list from finished-trace records (default: the
    collected ring). pid = engine tag, tid = request id, ts/dur in µs on
    the perf_counter timeline; instants export as ph='i'."""
    events = []
    seen_pids = {}
    seen_tids = set()
    for rec in (traces() if records is None else records):
        new_pid = rec["engine"] not in seen_pids
        pid = seen_pids.setdefault(rec["engine"], len(seen_pids) + 1)
        tid = rec["request_id"]
        for ev in rec["spans"]:
            ts = ev["t0"] * 1e6
            dur = (ev["t1"] - ev["t0"]) * 1e6
            args = {k: v for k, v in ev.items()
                    if k not in ("name", "t0", "t1")}
            if dur <= 0:
                events.append({"name": ev["name"], "ph": "i", "s": "t",
                               "pid": pid, "tid": tid, "ts": ts,
                               "args": args})
            else:
                events.append({"name": ev["name"], "ph": "X", "pid": pid,
                               "tid": tid, "ts": ts, "dur": dur,
                               "args": args})
        if new_pid:
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": f"serving:{rec['engine']}"}})
        if (pid, tid) not in seen_tids:
            seen_tids.add((pid, tid))
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"request {tid}"}})
    return events


def export_perfetto(path, records=None):
    """Write the collected request traces as Chrome-trace JSON (loads in
    Perfetto / chrome://tracing / TensorBoard). Returns the path."""
    payload = {"traceEvents": chrome_events(records),
               "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return path
