"""Model-FLOP estimators — the SINGLE source for every MFU number.

bench.py (the BENCH_* trajectory), tools_mfu_sweep.py and the live step
telemetry (observability/step_telemetry.py) all consume these, so the
offline bench numbers and the live in-run MFU can never diverge by using
different formulas.

Pure python on purpose: bench.py's parent process must stay jax-free
(signal safety), so nothing here may import jax at module scope.
"""
from __future__ import annotations


def peak_flops_bf16(device_kind: str) -> float:
    """Per-chip bf16 peak by device kind (marketing numbers; the MFU
    denominator)."""
    dk = (device_kind or "").lower()
    table = {
        "v6": 918e12, "v5p": 459e12, "v5 lite": 197e12, "v5e": 197e12,
        "v4": 275e12, "v3": 123e12, "v2": 45e12,
    }
    for k, v in table.items():
        if k in dk:
            return v
    return 197e12  # conservative default


def model_flops_per_token(cfg, seq_len):
    """GPT-family training FLOPs per token: 6N matmul + attention term
    (fwd+bwd). ``cfg`` needs hidden_size / num_layers / vocab_size /
    max_seq_len (GPTConfig or BertConfig-shaped). Returns
    (flops_per_token, n_params)."""
    H, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    n_params = 12 * L * H * H + V * H * 2 + cfg.max_seq_len * H
    attn = 12 * L * H * seq_len  # 2*2*S*H per layer fwd, x3 with bwd
    return 6 * n_params + attn, n_params


def dense_flops_per_token(n_params):
    """Transformer training FLOPs per token from the parameter count alone
    (the 6N rule) — for models counted by their live parameters (BERT in
    tools_mfu_sweep) rather than a config formula."""
    return 6 * int(n_params)


def train_step_flops(cfg, batch, seq_len):
    """Total training FLOPs of one (batch, seq) step — what the live step
    telemetry divides by step wall time for achieved FLOP/s."""
    fpt, n_params = model_flops_per_token(cfg, seq_len)
    return fpt * batch * seq_len, n_params


def mfu(flops, wall_s, peak_flops):
    """Achieved / peak; None when any input is missing or degenerate."""
    if not flops or not wall_s or not peak_flops:
        return None
    return (flops / wall_s) / peak_flops
