"""Live step telemetry for training loops.

With ``FLAGS_step_telemetry`` on, ``jit.TrainStep`` and
``models.gpt_hybrid.HybridTrainStep`` record a sampled per-step record
(every ``FLAGS_step_telemetry_every`` steps): wall time split into
dispatch (async jit call) and host-sync (block until the loss is real),
achieved MFU from the model's STATIC FLOP count
(observability/flops.py — the same estimator the bench uses, so live and
offline MFU cannot diverge), wire bytes from the static comm-schedule
records (grad_comm / tp_overlap), and device-memory watermarks via
``jax.live_arrays`` / per-device ``memory_stats``.

Wall time is averaged over the WINDOW since the previous sample (the
sampled step's own sync would otherwise absorb the drained async queue of
the unsampled steps in between and over-read), so sampling is cheap while
the number stays honest.

An EWMA regression sentinel tracks the rolling step-time baseline and
logs a warning whenever a sampled step drifts more than
``FLAGS_step_time_drift_pct`` above it — the "this run just got slower"
tripwire for long pretraining jobs.

Everything is host-side timing around the already-existing jit dispatch:
telemetry on/off never adds a traced operand or a retrace, and when off
the cost is one dict lookup per step.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque

_log = logging.getLogger("paddle_tpu.observability")

_lock = threading.Lock()
_records = deque(maxlen=4096)

_EWMA_ALPHA = 0.2
_WARMUP = 2  # samples ignored by the sentinel (compile + cache warm)


def _zero():
    # "last_*" fields are LATEST-SAMPLE values: with several live train
    # steps in one process they name whichever model sampled last (see
    # last_tag); per-model history is records() filtered by tag
    return {"steps_seen": 0, "sampled": 0, "drift_alerts": 0,
            "last_tag": None, "wall_ema_s": None, "last_wall_s": None,
            "last_dispatch_s": None, "last_sync_s": None,
            "last_mfu": None, "last_tokens_per_s": None,
            "wire_bytes_per_step": 0, "mem_bytes": 0, "mem_peak_bytes": 0,
            "flops_per_step": 0}


_S = _zero()


class _Sentinel:
    """EWMA baseline + warmup counter for the drift check. PER SAMPLER
    (each TrainStep owns one): a process sweeping several models must not
    compare one model's step time against another's baseline, nor let a
    later model's compile step burn the first one's warmup allowance."""

    __slots__ = ("ema", "n")

    def __init__(self):
        self.ema = None
        self.n = 0


_default_sentinel = _Sentinel()   # direct observe() callers (tests, tools)


def enabled():
    from ..flags import _FLAGS
    return bool(_FLAGS.get("FLAGS_step_telemetry", False))


def sample_every():
    from ..flags import _FLAGS
    try:
        return max(1, int(_FLAGS.get("FLAGS_step_telemetry_every", 8)))
    except (TypeError, ValueError):
        return 8


def should_sample(step_idx):
    """One cheap check per step: False when telemetry is off or this step
    is not on the sampling cadence."""
    if not enabled():
        return False
    with _lock:
        _S["steps_seen"] += 1
    return step_idx % sample_every() == 0


def _drift_pct():
    from ..flags import _FLAGS
    try:
        return float(_FLAGS.get("FLAGS_step_time_drift_pct", 25.0))
    except (TypeError, ValueError):
        return 25.0


def device_mem_bytes():
    """Best-effort device-memory watermark: live jax.Array bytes, plus the
    backend allocator's peak when it exposes memory_stats (TPU)."""
    live = peak = 0
    try:
        import jax
        live = int(sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", None)
            st = stats() if callable(stats) else None
            if st:
                peak += int(st.get("peak_bytes_in_use",
                                   st.get("bytes_in_use", 0)))
    except Exception:  # noqa: BLE001 — telemetry must never kill a step
        pass
    return live, peak


def observe(tag, step, wall_s, dispatch_s=None, sync_s=None, tokens=None,
            flops=None, wire_bytes=None, peak_flops=None, window=1,
            sentinel=None):
    """Record one sampled step. ``wall_s`` is the per-step average over
    the ``window`` steps since the previous sample. ``sentinel`` scopes
    the drift baseline (a ``StepSampler`` passes its own; direct callers
    share the module default). Returns the record."""
    from .flops import mfu as _mfu
    mem_live, mem_peak = device_mem_bytes()
    rec = {
        "tag": str(tag), "step": int(step), "wall_s": float(wall_s),
        "dispatch_s": None if dispatch_s is None else float(dispatch_s),
        "sync_s": None if sync_s is None else float(sync_s),
        "tokens": None if tokens is None else int(tokens),
        "flops": None if flops is None else float(flops),
        "wire_bytes": None if wire_bytes is None else int(wire_bytes),
        "mem_bytes": mem_live, "mem_peak_bytes": mem_peak,
        "window": int(window), "t": time.time(),
    }
    rec["tokens_per_s"] = (tokens / wall_s if tokens and wall_s > 0
                           else None)
    rec["mfu"] = _mfu(flops, wall_s, peak_flops)
    sb = _default_sentinel if sentinel is None else sentinel
    drift = None
    with _lock:
        _records.append(rec)
        _S["sampled"] += 1
        _S["last_tag"] = rec["tag"]
        _S["last_wall_s"] = rec["wall_s"]
        _S["last_dispatch_s"] = rec["dispatch_s"]
        _S["last_sync_s"] = rec["sync_s"]
        _S["last_mfu"] = rec["mfu"]
        _S["last_tokens_per_s"] = rec["tokens_per_s"]
        _S["mem_bytes"] = mem_live
        _S["mem_peak_bytes"] = max(_S["mem_peak_bytes"], mem_peak, mem_live)
        if wire_bytes is not None:
            _S["wire_bytes_per_step"] = int(wire_bytes)
        if flops is not None:
            _S["flops_per_step"] = float(flops)
        sb.n += 1
        pct = _drift_pct()
        if sb.n <= _WARMUP or sb.ema is None:
            # compile / first-dispatch samples would poison the baseline
            sb.ema = rec["wall_s"] if sb.n >= _WARMUP else None
        else:
            if pct > 0 and rec["wall_s"] > sb.ema * (1.0 + pct / 100.0):
                _S["drift_alerts"] += 1
                drift = (rec["wall_s"], sb.ema, pct)
            sb.ema = (_EWMA_ALPHA * rec["wall_s"]
                      + (1.0 - _EWMA_ALPHA) * sb.ema)
        _S["wall_ema_s"] = rec["wall_ema_s"] = sb.ema
    if drift is not None:
        w, ema, pct = drift
        _log.warning(
            "step-time regression: %s step %d took %.1fms, %.0f%% over the "
            "rolling baseline %.1fms (threshold %.0f%%)",
            tag, step, w * 1e3, (w / ema - 1.0) * 100.0, ema * 1e3, pct)
    return rec


def records():
    with _lock:
        return [dict(r) for r in _records]


def step_counters():
    """Snapshot of the live-step ledger (registry family "step")."""
    with _lock:
        return dict(_S)


def reset_step_telemetry():
    global _S, _default_sentinel
    with _lock:
        _S = _zero()
        _records.clear()
        _default_sentinel = _Sentinel()


def step_summary():
    """One-line human-readable live-step report."""
    c = step_counters()
    if not c["sampled"]:
        return "no sampled steps"
    fmt = lambda v, s=1e3, u="ms": ("n/a" if v is None  # noqa: E731
                                    else f"{v * s:.1f}{u}")
    mfu = "n/a" if c["last_mfu"] is None else f"{c['last_mfu'] * 100:.1f}%"
    tag = f" [{c['last_tag']}]" if c["last_tag"] else ""
    return (f"sampled: {c['sampled']}/{c['steps_seen']} steps{tag}  "
            f"wall: {fmt(c['last_wall_s'])} (ema {fmt(c['wall_ema_s'])})  "
            f"dispatch/sync: {fmt(c['last_dispatch_s'])}/"
            f"{fmt(c['last_sync_s'])}  mfu: {mfu}  "
            f"wire: {c['wire_bytes_per_step'] / 1e6:.2f}MB/step  "
            f"mem: {c['mem_bytes'] / 1e6:.0f}MB "
            f"(peak {c['mem_peak_bytes'] / 1e6:.0f}MB)  "
            f"drift-alerts: {c['drift_alerts']}")


# -- call-site helper ---------------------------------------------------------

class StepSampler:
    """The per-TrainStep host timer: owns the inter-sample window anchor
    so ``wall_s`` averages over unsampled steps too. Zero state when
    telemetry is off; both TrainStep flavors drive it identically::

        t0 = self._tel.begin(self._step)     # None when not sampling
        out = jitted(...)                     # async dispatch
        self._tel.end(t0, self._step, loss, tokens=..., flops=..., ...)
    """

    def __init__(self, tag):
        self.tag = tag
        self._anchor = None       # perf_counter at last sample end
        self._anchor_step = None
        self._peak = False        # False = not yet probed (None is valid)
        self._sentinel = _Sentinel()   # per-model drift baseline
        # every TrainStep flavor owns a sampler, so constructing one is
        # the training runtime's chokepoint for FLAGS_metrics_port (the
        # serving runtime's is Engine.__init__): bring the Prometheus
        # endpoint up when asked, no-op at the default 0
        from .prometheus import start_from_flags
        start_from_flags()

    def begin(self, step_idx):
        if not should_sample(step_idx):
            return None
        return time.perf_counter()

    def end(self, t0, step_idx, sync_arrays, tokens=None, flops=None,
            wire_bytes=None, peak_flops=None):
        if t0 is None:
            return None
        t1 = time.perf_counter()
        try:
            import jax
            jax.block_until_ready(sync_arrays)
        except Exception:  # noqa: BLE001
            pass
        t2 = time.perf_counter()
        if self._anchor is not None and step_idx > self._anchor_step:
            window = step_idx - self._anchor_step
            wall = (t2 - self._anchor) / window
        else:
            window = 1
            wall = t2 - t0
        self._anchor = t2
        self._anchor_step = step_idx
        if peak_flops is None:
            if self._peak is False:
                self._peak = default_peak_flops()
            peak_flops = self._peak
        return observe(self.tag, step_idx, wall, dispatch_s=t1 - t0,
                       sync_s=t2 - t1, tokens=tokens, flops=flops,
                       wire_bytes=wire_bytes, peak_flops=peak_flops,
                       window=window, sentinel=self._sentinel)


def default_peak_flops():
    """Per-process peak FLOP/s: per-chip bf16 peak x local device count."""
    try:
        import jax
        from .flops import peak_flops_bf16
        devs = jax.devices()
        return peak_flops_bf16(getattr(devs[0], "device_kind", "")) \
            * len(devs)
    except Exception:  # noqa: BLE001
        return None
