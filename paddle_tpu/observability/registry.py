"""Typed metrics registry — the single aggregation point for every ledger
in the framework.

Two kinds of members:

* **typed metrics** — `Counter` / `Gauge` / `Histogram` objects created
  through the registry with namespaced dotted keys (``serving.trace.spans``).
  New telemetry (span tracing, step telemetry, supervisor gauges) uses
  these directly.
* **families** — the six pre-existing counter ledgers (dispatch, comm,
  mp_comm, fault, serving, recovery) keep their zero-cost module-local
  bumping on the hot paths and REGISTER here as lazy collectors; a
  registry snapshot pulls them on demand. ``profiler.*_counters()`` are
  thin views over these collectors (bitwise-compatible with the
  pre-registry callers — the collector IS the old implementation).

Snapshot/delta semantics: ``snapshot()`` returns one flat
``{"family.metric": value}`` dict over every family and typed metric
(nested dicts flattened with dotted keys); ``delta(prev)`` subtracts two
snapshots' numeric entries — the per-window view a poll-based exporter
needs. The Prometheus exposition (observability/prometheus.py) renders a
snapshot; non-numeric entries (backend labels) are kept in the snapshot
but skipped by the exposition.

Thread-safety: one registry lock guards membership and typed-metric
mutation; family collectors take their own module locks (the same
discipline as ``profiler._events_lock``), so a snapshot taken while other
threads bump is internally consistent per family.
"""
from __future__ import annotations

import threading
from collections import deque

import numpy as np


class Counter:
    """Monotonic counter. ``inc`` is the only mutator."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name, lock):
        self.name = name
        self._v = 0
        self._lock = lock

    def inc(self, n=1):
        with self._lock:
            self._v += n

    @property
    def value(self):
        with self._lock:
            return self._v

    def _collect(self, out):
        out[self.name] = self.value


class Gauge:
    """Point-in-time value: ``set()`` a number, or back it with ``fn``
    (evaluated lazily at snapshot time — live queue depths, pool sizes)."""

    __slots__ = ("name", "_v", "_fn", "_lock")

    def __init__(self, name, lock, fn=None):
        self.name = name
        self._v = 0.0
        self._fn = fn
        self._lock = lock

    def set(self, v):
        with self._lock:
            self._v = v

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — a dead gauge must not
                return None    # poison the whole snapshot
        with self._lock:
            return self._v

    def _collect(self, out):
        out[self.name] = self.value


class Histogram:
    """Windowed distribution: a ring buffer of the LAST ``window``
    samples (late regressions must surface — same rationale as the
    serving TTFT ring), plus cumulative count/sum."""

    __slots__ = ("name", "_samples", "_count", "_sum", "_lock")

    def __init__(self, name, lock, window=65536):
        self.name = name
        self._samples = deque(maxlen=int(window))
        self._count = 0
        self._sum = 0.0
        self._lock = lock

    def observe(self, v):
        with self._lock:
            self._samples.append(float(v))
            self._count += 1
            self._sum += float(v)

    def percentile(self, p):
        with self._lock:
            s = list(self._samples)
        return float(np.percentile(s, p)) if s else None

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def _collect(self, out):
        with self._lock:
            s = list(self._samples)
            out[f"{self.name}.count"] = self._count
            out[f"{self.name}.sum"] = self._sum
        if s:
            out[f"{self.name}.p50"] = float(np.percentile(s, 50))
            out[f"{self.name}.p99"] = float(np.percentile(s, 99))


def _flatten(prefix, obj, out):
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    else:
        out[prefix] = obj


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}       # name -> Counter|Gauge|Histogram
        self._families = {}      # name -> zero-arg collector -> dict

    # -- typed metrics -------------------------------------------------------
    def _get_or_make(self, name, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, self._lock, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name):
        return self._get_or_make(name, Counter)

    def gauge(self, name, fn=None):
        g = self._get_or_make(name, Gauge)
        if fn is not None:
            g._fn = fn
        return g

    def histogram(self, name, window=65536):
        return self._get_or_make(name, Histogram, window=window)

    # -- families ------------------------------------------------------------
    def register_family(self, name, collector):
        """Register (or replace) a lazy counter family: ``collector`` is a
        zero-arg callable returning the family's current dict."""
        with self._lock:
            self._families[name] = collector

    def unregister_family(self, name):
        with self._lock:
            self._families.pop(name, None)

    def families(self):
        with self._lock:
            return tuple(sorted(self._families))

    def collect(self, family):
        """The family's current dict, exactly as its owning module reports
        it (the thin-view contract of ``profiler.*_counters()``)."""
        with self._lock:
            collector = self._families[family]
        return collector()

    # -- snapshot / delta ----------------------------------------------------
    def snapshot(self):
        """One flat {"family.metric": value} dict over every family and
        typed metric. Nested family dicts flatten with dotted keys."""
        out = {}
        with self._lock:
            fams = list(self._families.items())
            metrics = list(self._metrics.values())
        for name, collector in fams:
            try:
                _flatten(name, collector(), out)
            except Exception as e:  # noqa: BLE001 — one broken family
                out[f"{name}.collect_error"] = repr(e)  # must not hide rest
        for m in metrics:
            m._collect(out)
        return out

    def delta(self, prev, cur=None):
        """Numeric difference ``cur - prev`` between two snapshots (``cur``
        defaults to a fresh one). Keys missing from ``prev`` diff against
        0; non-numeric entries are skipped."""
        if cur is None:
            cur = self.snapshot()
        out = {}
        for k, v in cur.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            p = prev.get(k, 0)
            if isinstance(p, bool) or not isinstance(p, (int, float)):
                p = 0
            out[k] = v - p
        return out

    def reset_typed(self):
        """Drop every typed metric (test hygiene). Families are owned by
        their modules and keep their own reset entry points."""
        with self._lock:
            self._metrics.clear()


REGISTRY = MetricsRegistry()
