"""The framework's counter families, registered into the metrics registry.

Each collector returns EXACTLY what the pre-registry
``profiler.*_counters()`` returned — profiler keeps those names as thin
views over ``REGISTRY.collect(family)``, so existing callers are
bitwise-compatible while every family is now reachable from one snapshot
(and therefore from the Prometheus endpoint). Target modules are imported
lazily inside each collector: registering costs nothing, and the hot
paths keep their module-local zero-cost bumping.
"""
from __future__ import annotations

from .registry import REGISTRY


def _dispatch():
    from ..dispatch import cache_stats, cache_size
    stats = cache_stats()
    out = stats.as_dict()
    out["hit_rate"] = stats.hit_rate()
    out["cache_entries"] = cache_size()
    return out


def _comm():
    from ..distributed import grad_comm
    return grad_comm.comm_counters()


def _mp_comm():
    from ..distributed import tp_overlap
    return tp_overlap.mp_counters()


def _pp_comm():
    from ..distributed import pipeline
    return pipeline.pp_counters()


def _fault():
    from ..jit import train_step as _ts
    from ..incubate import checkpoint as _ck
    from ..utils import fault_injection as _fi
    return {"anomaly": _ts.anomaly_counters(),
            "checkpoint": _ck.ckpt_counters(),
            "injected": _fi.stats()}


def _serving():
    from ..serving import metrics
    return metrics.serving_counters()


def _sdc():
    from ..distributed import integrity
    return integrity.sdc_counters()


_RECOVERY_KEYS = ("snapshots", "snapshot_restores", "preempt_drains",
                  "requeued", "replayed", "respawns", "stale_failovers",
                  "rolling_restarts", "dropped")


def _recovery():
    c = _serving()
    return {k: c[k] for k in _RECOVERY_KEYS}


def _step():
    from . import step_telemetry
    return step_telemetry.step_counters()


def _elastic():
    from ..distributed import elastic as _el
    from ..distributed import topology as _topo
    out = dict(_el.elastic_counters())
    out.update(_topo.reshard_counters())
    return out


def register_default_families():
    """Idempotent: (re-)register the framework families. Called at
    observability import; safe to call again after a registry reset."""
    REGISTRY.register_family("dispatch", _dispatch)
    REGISTRY.register_family("comm", _comm)
    REGISTRY.register_family("mp_comm", _mp_comm)
    REGISTRY.register_family("pp_comm", _pp_comm)
    REGISTRY.register_family("fault", _fault)
    REGISTRY.register_family("serving", _serving)
    REGISTRY.register_family("recovery", _recovery)
    REGISTRY.register_family("step", _step)
    REGISTRY.register_family("elastic", _elastic)
    REGISTRY.register_family("sdc", _sdc)


def register_supervisor(sup):
    """Expose a ServingSupervisor's live per-replica gauges as the
    "supervisor" family. Weakly referenced: the family reports {} once the
    supervisor is garbage-collected (a later supervisor simply replaces
    the registration)."""
    import weakref
    ref = weakref.ref(sup)

    def collect():
        s = ref()
        if s is None:
            return {}
        return s.telemetry()

    REGISTRY.register_family("supervisor", collect)
    return collect
