"""Graph learning primitives (ref: python/paddle/geometric/__init__.py,
message_passing/send_recv.py, reindex.py, sampling/neighbors.py).

TPU-native split of responsibilities:

* message passing (`send_u_recv`, `send_ue_recv`, `send_uv`, `segment_*`)
  lowers to XLA gather + segment-reduce (scatter-add/min/max), which TPU
  executes as vectorized dynamic-update ops — jit/grad compatible when
  `out_size`/`num_segments` is static.
* structure ops with data-dependent output shapes (`reindex_graph`,
  `sample_neighbors`) run host-side on numpy, mirroring how the reference
  runs them as CPU preprocessing before the dense compute; XLA requires
  static shapes so these belong on the host by design.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..dispatch import apply
from ..tensor_impl import Tensor, as_tensor_data

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "reindex_graph", "reindex_heter_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]

_MESSAGE_OPS = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide,
}

_sample_rng = None


def _host_rng():
    """Persistent host-side RNG for neighbor sampling: seeded from
    `paddle.seed` when set, advances across calls so each sampling step
    draws a fresh subgraph."""
    global _sample_rng
    if _sample_rng is None:
        from ..framework.random import get_seed
        s = get_seed()
        _sample_rng = np.random.RandomState(s if s is not None else None)
    return _sample_rng


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    return int(np.asarray(jax.device_get(ids)).max()) + 1 if ids.size else 0


def _segment_reduce(data, ids, pool, num_segments):
    if pool == "sum":
        return jax.ops.segment_sum(data, ids, num_segments)
    if pool == "mean":
        tot = jax.ops.segment_sum(data, ids, num_segments)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype), ids,
                                  num_segments)
        return tot / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (data.ndim - 1))
    if pool == "min":
        out = jax.ops.segment_min(data, ids, num_segments)
    elif pool == "max":
        out = jax.ops.segment_max(data, ids, num_segments)
    else:
        raise ValueError(f"reduce_op should be sum/mean/min/max, got {pool}")
    # empty segments come back as +/-inf identity; the reference zeros them
    cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],)), ids, num_segments)
    mask = (cnt > 0).reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(mask, out, jnp.zeros_like(out))


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(as_tensor_data(segment_ids), None)
    return apply(lambda d, i: _segment_reduce(d, i, "sum", n), data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(as_tensor_data(segment_ids), None)
    return apply(lambda d, i: _segment_reduce(d, i, "mean", n), data, segment_ids)


def segment_min(data, segment_ids, name=None):
    n = _num_segments(as_tensor_data(segment_ids), None)
    return apply(lambda d, i: _segment_reduce(d, i, "min", n), data, segment_ids)


def segment_max(data, segment_ids, name=None):
    n = _num_segments(as_tensor_data(segment_ids), None)
    return apply(lambda d, i: _segment_reduce(d, i, "max", n), data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges and reduce at destinations."""
    n = out_size if out_size is not None else as_tensor_data(x).shape[0]
    return apply(
        lambda xv, s, d: _segment_reduce(jnp.take(xv, s, axis=0), d,
                                         reduce_op, int(n)),
        x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add", reduce_op="sum",
                 out_size=None, name=None):
    """Combine source-node features with edge features, then reduce."""
    if message_op not in _MESSAGE_OPS:
        raise ValueError(f"message_op should be add/sub/mul/div, got {message_op}")
    n = out_size if out_size is not None else as_tensor_data(x).shape[0]
    op = _MESSAGE_OPS[message_op]
    return apply(
        lambda xv, yv, s, d: _segment_reduce(op(jnp.take(xv, s, axis=0), yv),
                                             d, reduce_op, int(n)),
        x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from source and destination node features."""
    if message_op not in _MESSAGE_OPS:
        raise ValueError(f"message_op should be add/sub/mul/div, got {message_op}")
    op = _MESSAGE_OPS[message_op]
    return apply(
        lambda xv, yv, s, d: op(jnp.take(xv, s, axis=0),
                                jnp.take(yv, d, axis=0)),
        x, y, src_index, dst_index)


# -- host-side structure ops -------------------------------------------------

def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact a sampled subgraph's node ids to a dense [0, n) range."""
    xs = np.asarray(jax.device_get(as_tensor_data(x)))
    nb = np.asarray(jax.device_get(as_tensor_data(neighbors)))
    cnt = np.asarray(jax.device_get(as_tensor_data(count)))
    order = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    for v in nb:
        v = int(v)
        if v not in order:
            order[v] = len(out_nodes)
            out_nodes.append(v)
    reindex_src = np.array([order[int(v)] for v in nb], np.int64)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.array(out_nodes, np.int64))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are per-edge-type lists."""
    cat_nb = np.concatenate(
        [np.asarray(jax.device_get(as_tensor_data(n))) for n in neighbors])
    cat_cnt_parts = [np.asarray(jax.device_get(as_tensor_data(c)))
                     for c in count]
    src, dst, nodes = reindex_graph(x, Tensor(jnp.asarray(cat_nb)),
                                    Tensor(jnp.asarray(np.concatenate(cat_cnt_parts))))
    # dst must restart per edge type over the same seed nodes
    xs = np.asarray(jax.device_get(as_tensor_data(x)))
    dsts = [np.repeat(np.arange(len(xs), dtype=np.int64), c)
            for c in cat_cnt_parts]
    return src, Tensor(jnp.asarray(np.concatenate(dsts))), nodes


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniformly sample up to `sample_size` in-neighbors per seed node (CSC)."""
    r = np.asarray(jax.device_get(as_tensor_data(row)))
    cp = np.asarray(jax.device_get(as_tensor_data(colptr)))
    seeds = np.asarray(jax.device_get(as_tensor_data(input_nodes)))
    rng = _host_rng()
    out_n, out_c, out_e = [], [], []
    ev = (np.asarray(jax.device_get(as_tensor_data(eids)))
          if eids is not None else None)
    for node in seeds:
        beg, end = int(cp[node]), int(cp[node + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(beg, end)
        else:
            pick = beg + rng.choice(deg, sample_size, replace=False)
        out_n.append(r[pick])
        out_c.append(len(pick))
        if ev is not None:
            out_e.append(ev[pick])
    neighbors = Tensor(jnp.asarray(np.concatenate(out_n) if out_n else
                                   np.zeros((0,), r.dtype)))
    counts = Tensor(jnp.asarray(np.array(out_c, np.int64)))
    if return_eids:
        e = Tensor(jnp.asarray(np.concatenate(out_e) if out_e else
                               np.zeros((0,), np.int64)))
        return neighbors, counts, e
    return neighbors, counts


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted (probability ∝ edge weight) neighbor sampling."""
    r = np.asarray(jax.device_get(as_tensor_data(row)))
    cp = np.asarray(jax.device_get(as_tensor_data(colptr)))
    w = np.asarray(jax.device_get(as_tensor_data(edge_weight)), np.float64)
    seeds = np.asarray(jax.device_get(as_tensor_data(input_nodes)))
    rng = _host_rng()
    out_n, out_c, out_e = [], [], []
    ev = (np.asarray(jax.device_get(as_tensor_data(eids)))
          if eids is not None else None)
    for node in seeds:
        beg, end = int(cp[node]), int(cp[node + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(beg, end)
        else:
            p = w[beg:end] / w[beg:end].sum()
            pick = beg + rng.choice(deg, sample_size, replace=False, p=p)
        out_n.append(r[pick])
        out_c.append(len(pick))
        if ev is not None:
            out_e.append(ev[pick])
    neighbors = Tensor(jnp.asarray(np.concatenate(out_n) if out_n else
                                   np.zeros((0,), r.dtype)))
    counts = Tensor(jnp.asarray(np.array(out_c, np.int64)))
    if return_eids:
        e = Tensor(jnp.asarray(np.concatenate(out_e) if out_e else
                               np.zeros((0,), np.int64)))
        return neighbors, counts, e
    return neighbors, counts
