"""Metrics (ref: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..tensor_impl import Tensor, as_tensor_data


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(as_tensor_data(pred))
        label_np = np.asarray(as_tensor_data(label))
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = top == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(as_tensor_data(correct))
        n = c.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += n
        res = [t / max(cnt, 1) for t, cnt in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(as_tensor_data(preds)).round().astype(int).reshape(-1)
        l = np.asarray(as_tensor_data(labels)).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(as_tensor_data(preds)).round().astype(int).reshape(-1)
        l = np.asarray(as_tensor_data(labels)).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(as_tensor_data(preds))
        if p.ndim == 2:
            p = p[:, -1]
        l = np.asarray(as_tensor_data(labels)).reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(int), 0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (ref: python/paddle/metric/metrics.py
    accuracy)."""
    import jax.numpy as jnp
    from ..tensor_impl import as_tensor_data, wrap
    logits = as_tensor_data(input)
    lab = as_tensor_data(label)
    if lab.ndim == logits.ndim:
        lab = lab.reshape(lab.shape[:-1])
    topk = jnp.argsort(-logits, axis=-1)[..., :k]
    hit = jnp.any(topk == lab[..., None], axis=-1)
    return wrap(jnp.mean(hit.astype(jnp.float32)))
