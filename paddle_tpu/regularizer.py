"""Weight-decay regularizers (ref: python/paddle/regularizer.py)."""
from __future__ import annotations


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay:
    """L1 decay: applied eagerly as sign(p)*coeff added to grads."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay({self._coeff})"
