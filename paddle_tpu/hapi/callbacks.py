"""Training callbacks (ref: python/paddle/hapi/callbacks.py).

Same lifecycle protocol as the reference: on_{train,eval,predict}_{begin,end},
on_epoch_{begin,end}, on_{train,eval,predict}_batch_{begin,end}.
"""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks, model, params):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def call(self, name, *args, **kwargs):
        for c in self.callbacks:
            getattr(c, name)(*args, **kwargs)


class ProgBarLogger(Callback):
    """Prints metrics every `log_freq` steps (ref hapi ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _fmt(self, logs):
        return " - ".join(f"{k}: {v:.4f}" if isinstance(v, (int, float)) else f"{k}: {v}"
                          for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            print(f"step {step}: {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Saves model + optimizer state every `save_freq` epochs (ref hapi)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (ref hapi LRScheduler callback)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    """Stop training when `monitor` stops improving (ref hapi EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.verbose = verbose
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = np.inf
        self.wait = 0
        self.stopped_epoch = -1

    def on_train_begin(self, logs=None):
        if self.baseline is not None:
            self.best = self.baseline
        self.wait = 0

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"early stopping: no {self.monitor} improvement "
                          f"for {self.wait} evals")


def config_callbacks(callbacks, model, epochs=None, steps=None, verbose=2,
                     log_freq=1, save_freq=1, save_dir=None, metrics=None):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs):
        cbs.append(ProgBarLogger(log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbs):
        cbs.append(LRScheduler())
    params = {"epochs": epochs, "steps": steps, "verbose": verbose,
              "metrics": metrics or []}
    return CallbackList(cbs, model, params)


class ReduceLROnPlateau(Callback):
    """Reduce optimizer LR when `monitor` plateaus (ref hapi callbacks
    ReduceLROnPlateau — callback wrapper over the scheduler semantics)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = np.inf
        self.wait = 0
        self.cooldown_counter = 0

    def _optimizer(self):
        return getattr(self.model, "_optimizer", None)

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self._optimizer()
                if opt is not None:
                    old = float(opt.get_lr())
                    new = max(old * self.factor, self.min_lr)
                    if old - new > 1e-12:
                        try:
                            opt.set_lr(new)
                        except RuntimeError:
                            return  # LRScheduler-driven: leave it alone
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old:g} -> {new:g}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """VisualDL scalar logging (ref hapi callbacks VisualDL). The visualdl
    package is CUDA-ecosystem tooling not present here; scalars are written
    as jsonl the dashboard (or anything else) can ingest."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json
        import os
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, "scalars.jsonl")
        row = {"step": self._step, "tag": tag}
        for k, v in (logs or {}).items():
            try:
                row[k] = float(np.asarray(v).reshape(-1)[0])
            except Exception:
                continue
        with open(path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class WandbCallback(Callback):
    """Weights & Biases logging (ref hapi callbacks WandbCallback): uses the
    wandb package when importable, else raises at construction (zero-egress
    images ship without it)."""

    def __init__(self, project=None, **kwargs):
        super().__init__()
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the `wandb` package") from e
        import wandb
        self._wandb = wandb
        self._run = wandb.init(project=project, **kwargs)

    def on_train_batch_end(self, step, logs=None):
        self._wandb.log({k: float(np.asarray(v).reshape(-1)[0])
                         for k, v in (logs or {}).items()
                         if np.isscalar(v) or np.asarray(v).size == 1})
