"""High-level API: paddle.Model with fit/evaluate/predict + callbacks.

Re-design of the reference hapi (ref: python/paddle/hapi/model.py,
python/paddle/hapi/callbacks.py). The reference routes through dygraph or a
static-graph Executor; here the train step is the eager tape path (simple,
debuggable) with an optional jit'd fused step for throughput.
"""
from .model import Model, summary  # noqa: F401
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping,
)
