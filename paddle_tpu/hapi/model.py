"""paddle.Model high-level training loop (ref: python/paddle/hapi/model.py).

The reference dispatches to DynamicGraphAdapter/StaticGraphAdapter; TPU-native
there is one path: eager tape training (XLA-compiled per-op), with
`Model.prepare(..., jit=True)` switching to a fused jit'd train step
(jax.value_and_grad + optimizer update in one XLA program).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .. import optimizer as opt_mod
from ..tensor_impl import Tensor
from ..io import DataLoader, Dataset
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


class Model:
    """Train/eval/predict harness around an nn.Layer."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._mesh = None
        self._amp_level = None
        self._amp_dtype = "bfloat16"
        self._scaler = None
        self._train_step = None
        self._eval_jitted = None
        self.stop_training = False

    # -- configuration ----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, jit=False,
                mesh=None, amp_level=None, amp_dtype="bfloat16"):
        """Configure the loop. TPU-native extensions over the reference
        (ref python/paddle/hapi/model.py Model.prepare, whose distributed
        path wraps the net in Fleet's DataParallel):

        - mesh: a jax.sharding.Mesh — fit() runs a single compiled
          TrainStep with params replicated and the batch sharded over the
          mesh's 'dp'/'sdp' axes; XLA inserts the gradient all-reduce the
          reference gets from ProcessGroupNCCL.
        - amp_level: 'O1' traces the step under amp.auto_cast (white ops
          in bf16/fp16 on the MXU); 'O2' casts params via amp.decorate and
          enables master weights. float16 + eager adds GradScaler loss
          scaling; bfloat16 needs none.
        """
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        self._jit = jit
        self._mesh = mesh
        self._amp_level = amp_level
        self._amp_dtype = amp_dtype
        self._train_step = None
        self._scaler = None
        self._eval_jitted = None  # re-prepare must re-trace with the new loss
        if optimizer is not None and getattr(optimizer, "_parameter_list", None) is None:
            optimizer._parameter_list = list(self.network.parameters())
        compiled = (jit or mesh is not None) and optimizer is not None \
            and loss is not None
        if compiled and amp_level is not None and amp_dtype == "float16":
            # validate BEFORE decorate: O2 decorate casts params in place,
            # and a caller catching this error must be able to re-prepare
            # from unmodified weights
            raise ValueError(
                "float16 AMP needs GradScaler loss scaling, which the "
                "compiled TrainStep path does not integrate; use "
                "amp_dtype='bfloat16' (the TPU-native choice, no "
                "scaling needed) or the eager path (jit=False, no mesh)")
        if amp_level == "O2":
            from .. import amp as amp_mod
            if optimizer is not None:
                amp_mod.decorate(self.network, optimizer, level="O2",
                                 dtype=amp_dtype)
            else:
                amp_mod.decorate(self.network, level="O2", dtype=amp_dtype)
        if compiled:
            from ..jit.train_step import TrainStep
            self._train_step = TrainStep(self.network, loss, optimizer,
                                         mesh=mesh)
        elif amp_level is not None and amp_dtype == "float16":
            from ..amp import GradScaler
            self._scaler = GradScaler()
        return self

    def _amp_ctx(self):
        if self._amp_level is None:
            import contextlib
            return contextlib.nullcontext()
        from ..amp import auto_cast
        return auto_cast(level=self._amp_level, dtype=self._amp_dtype)

    def parameters(self):
        return list(self.network.parameters())

    # -- single-batch ops (public parity: train_batch/eval_batch/predict_batch)
    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        labels = [_as_tensor(x) for x in _to_list(labels)]
        if self._train_step is not None:
            # auto_cast matters at trace time only (first call compiles);
            # harmless afterwards.
            with self._amp_ctx():
                loss = self._train_step(
                    inputs[0] if len(inputs) == 1 else inputs,
                    labels[0] if len(labels) == 1 else labels)
            self._train_step.sync_to_model()
            return [float(loss)], self._metric_logs()
        self._optimizer.clear_grad()
        with self._amp_ctx():
            outputs = self.network(*inputs)
            loss = self._loss(outputs, *labels) if labels else self._loss(outputs)
        if self._scaler is not None:
            self._scaler.scale(loss).backward()
            # step() runs unscale_ + optimizer.step + update() internally
            self._scaler.step(self._optimizer)
        else:
            loss.backward()
            self._optimizer.step()
        self._update_metrics(outputs, labels)
        return [float(loss)], self._metric_logs()

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        labels = [_as_tensor(x) for x in _to_list(labels)]
        if self._train_step is not None and self._loss is not None and labels:
            # compiled eval: one XLA program over the step's live (possibly
            # mesh-sharded) params instead of eager per-op dispatch
            loss, outputs = self._compiled_eval(inputs, labels)
            self._update_metrics(outputs, labels)
            return [float(loss)], self._metric_logs()
        outputs = self.network(*inputs)
        loss = self._loss(outputs, *labels) if self._loss and labels else None
        self._update_metrics(outputs, labels)
        return ([float(loss)] if loss is not None else []), self._metric_logs()

    def _compiled_eval(self, inputs, labels):
        import jax
        step = self._train_step
        if self._eval_jitted is None:
            self._eval_jitted = step.build_eval()
        in_arrays = tuple(x._data for x in inputs)
        lab_arrays = tuple(x._data for x in labels)
        loss, out = self._eval_jitted(step._params, step._buffers,
                                      in_arrays, lab_arrays)
        return loss, jax.tree_util.tree_map(Tensor, out)

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        out = self.network(*inputs)
        return [o.numpy() for o in _to_list(out)]

    def _update_metrics(self, outputs, labels):
        for m in self._metrics:
            try:
                res = m.compute(outputs, *labels) if labels else m.compute(outputs)
                m.update(res)
            except Exception:
                pass

    def _metric_logs(self):
        logs = {}
        for m in self._metrics:
            try:
                name = m.name() if callable(getattr(m, "name", None)) else type(m).__name__
                acc = m.accumulate()
                if isinstance(name, (list, tuple)):
                    logs.update(dict(zip(name, _to_list(acc))))
                else:
                    logs[name] = acc
            except Exception:
                pass
        return logs

    # -- loops -------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset) or hasattr(data, "__getitem__"):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        return data  # generator of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            ckpt_dir=None, ckpt_freq=0, resume=False):
        """Training loop. Fault-tolerance extensions over the reference:

        - ckpt_dir/ckpt_freq: every ``ckpt_freq`` batches the COMPLETE
          training state (weights, optimizer slots, RNG stream, epoch/batch
          position) goes to a hardened ``incubate.checkpoint
          .CheckpointManager`` under ``ckpt_dir``; while fitting, a SIGTERM
          preemption hook flushes a final blocking save before the process
          dies.
        - resume=True: restore the latest good checkpoint from ``ckpt_dir``
          and continue mid-epoch — on the compiled TrainStep path the
          resumed run reproduces the uninterrupted parameter trajectory
          bitwise (RNG stream, in-epoch shuffle order, and batch position
          are all part of the state).
        """
        from ..framework import random as _rnd
        loader = self._loader(train_data, batch_size, shuffle)
        mgr = None
        resume_epoch, resume_batch, resume_rng = 0, 0, None
        if ckpt_dir is not None:
            from ..incubate.checkpoint import CheckpointManager
            mgr = CheckpointManager(ckpt_dir, async_save=False)
            if resume:
                if not isinstance(loader, DataLoader):
                    raise ValueError(
                        "fit(resume=True) needs train_data to be a Dataset "
                        "or DataLoader (position tracking)")
                st = mgr.restore(None)
                if st is not None:
                    resume_epoch = int(st["epoch"])
                    resume_batch = int(st["batch"])
                    resume_rng = st["rng"]
                    self._load_fit_state(st)
                    try:
                        epoch_len = len(loader)
                    except TypeError:
                        epoch_len = None
                    if epoch_len is not None and resume_batch >= epoch_len:
                        # saved at the last batch of an epoch: roll to the
                        # next epoch instead of replaying this one empty
                        # (which would re-fire on_epoch_end with no logs
                        # and re-run eval); the stream is already at its
                        # end-of-epoch position
                        resume_epoch += 1
                        resume_batch = 0
                        _rnd.set_state_dict(st["rng"])
                    else:
                        # replay the epoch's shuffle from its recorded
                        # start: the iterator below redraws the same
                        # permutation, the skip consumes indices only, and
                        # resume_rng then realigns the stream to the batch
                        # position
                        _rnd.set_state_dict(st["rng_epoch_start"])
        cbks = config_callbacks(callbacks, self, epochs=epochs, verbose=verbose,
                                log_freq=log_freq, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metrics)
        self.stop_training = False
        cbks.call("on_train_begin")
        logs = {}
        pos = {"epoch": resume_epoch, "batch": resume_batch,
               "rng_epoch_start": None}
        if mgr is not None:
            # deferred: the handler only marks preempted; the loop flushes
            # at the next batch boundary, where weights/RNG/position are a
            # consistent snapshot (mid-step the donated params are dead)
            mgr.install_preemption_hook(lambda: self._fit_state(**pos),
                                        defer=True)
        try:
            global_batch = 0
            # monotonic save tags across resumes: never publish a step id
            # below one already on disk (rename-aside makes an overwrite
            # safe, but a resumed run must not shadow a newer checkpoint)
            step_base = (mgr.latest_step() or 0) if mgr is not None else 0
            for epoch in range(resume_epoch, epochs):
                if self.stop_training:
                    break
                for m in self._metrics:
                    m.reset()
                cbks.call("on_epoch_begin", epoch)
                logs = {}
                pos["epoch"], pos["batch"] = epoch, resume_batch
                pos["rng_epoch_start"] = _rnd.state_dict()
                if resume_batch and isinstance(loader, DataLoader):
                    loader.load_state_dict({"batches_served": resume_batch})
                it = iter(loader)
                step = resume_batch
                if resume_batch:
                    # the first next() above-skip draws the epoch
                    # permutation from the pre-epoch RNG; after that the
                    # saved stream position takes over so every subsequent
                    # key matches the uninterrupted run
                    batch = next(it, None)
                    _rnd.set_state_dict(resume_rng)
                    resume_batch, resume_rng = 0, None
                else:
                    batch = next(it, None)
                while batch is not None:
                    batch = _to_list(batch)
                    ins, labs = batch[:-1] or batch, batch[-1:]
                    cbks.call("on_train_batch_begin", step)
                    losses, metrics = self.train_batch(ins, labs)
                    logs = {"loss": losses[0] if losses else None, **metrics}
                    cbks.call("on_train_batch_end", step, logs)
                    step += 1
                    global_batch += 1
                    pos["batch"] = step
                    if mgr is not None and mgr.preempted:
                        mgr.flush_preempted(self._fit_state(**pos),
                                            step=step_base + global_batch)
                    if mgr is not None and ckpt_freq and \
                            global_batch % ckpt_freq == 0:
                        mgr.save(step_base + global_batch,
                                 self._fit_state(**pos))
                    batch = next(it, None)
                cbks.call("on_epoch_end", epoch, logs)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_data, batch_size=batch_size, verbose=0,
                                  callbacks=cbks.callbacks, _nested=True)
        finally:
            if mgr is not None:
                mgr.remove_preemption_hook()
        cbks.call("on_train_end", logs)

    # -- fault-tolerant fit state -------------------------------------------
    def _fit_state(self, epoch, batch, rng_epoch_start):
        """Complete fit-loop state: model/optimizer (TrainStep.state_dict on
        the compiled path), position, and the two RNG anchors the resume
        protocol needs (stream at epoch start for the shuffle replay, and
        current stream for everything after the skip)."""
        from ..framework import random as _rnd
        state = {"epoch": int(epoch), "batch": int(batch),
                 "rng_epoch_start": rng_epoch_start or _rnd.state_dict(),
                 "rng": _rnd.state_dict()}
        if self._train_step is not None:
            state["kind"] = "train_step"
            state["ts"] = self._train_step.state_dict()
        else:
            state["kind"] = "eager"
            state["net"] = self.network.state_dict()
            if self._optimizer is not None:
                state["opt"] = getattr(self._optimizer, "state_dict",
                                       dict)()
            if self._scaler is not None:
                state["scaler"] = self._scaler.state_dict()
        return state

    def _load_fit_state(self, state):
        if state.get("kind") == "train_step" and self._train_step is not None:
            self._train_step.load_state_dict(state["ts"])
        else:
            self.network.set_state_dict(state["net"])
            if "opt" in state and self._optimizer is not None and \
                    hasattr(self._optimizer, "set_state_dict"):
                self._optimizer.set_state_dict(state["opt"])
            if "scaler" in state and self._scaler is not None:
                self._scaler.load_state_dict(dict(state["scaler"]))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _nested=False):
        loader = self._loader(eval_data, batch_size, False)
        cbks = config_callbacks(callbacks if not _nested else None, self,
                                verbose=verbose, metrics=self._metrics) \
            if not _nested else None
        for m in self._metrics:
            m.reset()
        if cbks:
            cbks.call("on_eval_begin")
        logs = {}
        total_loss, n = 0.0, 0
        for step, batch in enumerate(loader):
            batch = _to_list(batch)
            ins, labs = batch[:-1] or batch, batch[-1:]
            losses, metrics = self.eval_batch(ins, labs)
            if losses:
                total_loss += losses[0]
                n += 1
            logs = {**({"loss": total_loss / max(n, 1)} if n else {}), **metrics}
        if cbks:
            cbks.call("on_eval_end", logs)
        elif _nested:
            for c in (callbacks or []):
                c.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        loader = self._loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            batch = _to_list(batch)
            outs.append(self.predict_batch(batch[:1]))
        if stack_outputs and outs:
            k = len(outs[0])
            return [np.concatenate([o[i] for o in outs], axis=0) for i in range(k)]
        return outs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        from ..framework.io import save as psave
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            state = getattr(self._optimizer, "state_dict", lambda: {})()
            with open(path + ".pdopt", "wb") as f:
                pickle.dump(_host_tree(state), f)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        self.network.set_state_dict(pload(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            with open(opt_path, "rb") as f:
                state = pickle.load(f)
            if hasattr(self._optimizer, "set_state_dict"):
                self._optimizer.set_state_dict(state)


def _host_tree(tree):
    import jax
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a) if hasattr(a, "shape") else a, tree)


def summary(net, input_size=None, dtypes=None, input=None):
    """Print + return layer/param summary (ref hapi.summary)."""
    rows = []
    total = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=10) + 2
    print(f"{'Param':<{width}}{'Shape':<20}{'#':>12}")
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<20}{n:>12,}")
    print(f"Total params: {total:,}")
    return {"total_params": total, "trainable_params": total}
