"""paddle.sparse.nn (ref: python/paddle/sparse/nn/layer/).

Sparse layers over SparseCooTensor activations: submanifold / regular
sparse conv (gather -> dense GEMM -> segment scatter, MXU-friendly),
BatchNorm over values, activations, sparse max pooling, and sparse
attention. See functional/__init__.py for the compute design.
"""
from __future__ import annotations

import numpy as np

from ...nn.layer_base import Layer
from ...nn import BatchNorm1D
from ...nn import initializer as _I
from . import functional as F  # noqa: N812


class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, subm,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        assert padding_mode == "zeros"
        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._kernel_size = ks
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._subm = subm
        self._data_format = data_format
        # reference weight layout: kernel_size + [in/groups, out]
        shape = list(ks) + [in_channels // groups, out_channels]
        fan_in = in_channels * int(np.prod(ks)) // groups
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(shape=shape, attr=weight_attr,
                                            dtype=self._dtype)
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, dtype=self._dtype,
            is_bias=True,
            default_initializer=_I.Uniform(-bound, bound)
            if bias_attr is None else None)

    def extra_repr(self):
        return (f"kernel_size={self._kernel_size}, stride={self._stride}, "
                f"subm={self._subm}")


class Conv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, False,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class SubmConv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        if stride not in (1, (1, 1, 1), [1, 1, 1]):
            raise NotImplementedError(
                "SubmConv3D: submanifold conv preserves coordinates; "
                "stride must be 1")
        super().__init__(in_channels, out_channels, kernel_size, 3, True,
                         1, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)
        self._key = key

    def forward(self, x):
        return F.subm_conv3d(x, self.weight, self.bias, 1, self._padding,
                             self._dilation, self._groups, self._data_format,
                             key=self._key)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, False,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        if stride not in (1, (1, 1), [1, 1]):
            raise NotImplementedError(
                "SubmConv2D: submanifold conv preserves coordinates; "
                "stride must be 1")
        super().__init__(in_channels, out_channels, kernel_size, 2, True,
                         1, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)
        self._key = key

    def forward(self, x):
        return F.subm_conv2d(x, self.weight, self.bias, 1, self._padding,
                             self._dilation, self._groups, self._data_format,
                             key=self._key)


class BatchNorm(BatchNorm1D):
    """BatchNorm over sparse values [nnz, C] (ref sparse/nn/layer/norm.py
    BatchNorm, which also runs dense BN on the values view)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum=momentum, epsilon=epsilon,
                         weight_attr=weight_attr, bias_attr=bias_attr)
        self._sparse_data_format = data_format

    def forward(self, x):
        from ...tensor_impl import Tensor
        from .functional import _coo_with_tensor_values, _values_input
        vals = x.values if isinstance(x.values, Tensor) \
            else Tensor(_values_input(x))
        out = super().forward(vals)
        return _coo_with_tensor_values(x.indices, out, x.shape)


class SyncBatchNorm(BatchNorm):
    """On a mesh the dense BN stats reduce globally under GSPMD — sync is
    the compiled default (ref sparse/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer, cls):
            new = cls(layer._num_features, momentum=layer._momentum,
                      epsilon=layer._epsilon)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        for name, sub in list(getattr(layer, "_sub_layers", {}).items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        if return_mask:
            raise NotImplementedError(
                "sparse MaxPool3D: return_mask is not supported")
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._ceil_mode = ceil_mode
        self._data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self._kernel_size, self._stride,
                            self._padding, self._ceil_mode, self._data_format)


__all__ = [
    "Conv3D", "SubmConv3D", "Conv2D", "SubmConv2D", "BatchNorm",
    "SyncBatchNorm", "ReLU", "ReLU6", "LeakyReLU", "Softmax", "MaxPool3D",
    "functional",
]
