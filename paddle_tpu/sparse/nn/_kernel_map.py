"""Kernel-map construction for sparse N-D convolution.

The reference computes rulebooks in CUDA (ref: paddle/phi/kernels/sparse/
gpu/conv_kernel.cu, python/paddle/sparse/nn/layer/conv.py). TPU-native
design: coordinates live on host (the sparse API is eager, like the
reference's), the kernel map is built with vectorized numpy hashing, and
the actual compute is a gather -> dense GEMM (MXU) -> segment scatter per
kernel offset, executed by XLA on device.
"""
from __future__ import annotations

import numpy as np


def flatten_coords(coords, spatial):
    """coords [n, 1+nd] (batch, *spatial) -> unique int64 key per coord."""
    key = coords[:, 0].astype(np.int64)
    for d, size in enumerate(spatial):
        key = key * int(size) + coords[:, 1 + d]
    return key


def decode_keys(keys, spatial):
    coords = []
    rem = keys.astype(np.int64)
    for size in reversed(spatial):
        coords.append(rem % int(size))
        rem = rem // int(size)
    coords.append(rem)  # batch
    return np.stack(list(reversed(coords)), axis=1)


def kernel_offsets(kernel):
    """All kernel offsets in row-major order matching weight.reshape(-1, ...)."""
    grids = np.meshgrid(*[np.arange(k) for k in kernel], indexing="ij")
    return np.stack([g.ravel() for g in grids], axis=1)


def build_kernel_map(coords, spatial, kernel, stride, padding, dilation, subm,
                     ceil_mode=False):
    """coords: np.int64 [nnz, 1+nd]. Returns (out_coords [m, 1+nd],
    out_spatial, pairs) where pairs[k] = (in_idx, out_idx) arrays giving, for
    kernel offset k, which input point contributes to which output point."""
    nd = len(spatial)
    spatial = np.asarray(spatial, np.int64)
    kernel = np.asarray(kernel, np.int64)
    stride = np.asarray(stride, np.int64)
    padding = np.asarray(padding, np.int64)
    dilation = np.asarray(dilation, np.int64)
    offsets = kernel_offsets(kernel)
    n = coords.shape[0]

    if subm:
        # Submanifold: output coordinates == input coordinates (stride 1);
        # pair (i -> j) exists when coords[i] == coords[j] + (k - c) * dil.
        keys = flatten_coords(coords, spatial)
        order = np.argsort(keys)
        skeys = keys[order]
        center = (kernel - 1) // 2 * dilation
        pairs = []
        for off in offsets:
            delta = off * dilation - center
            cand = coords.copy()
            cand[:, 1:] = coords[:, 1:] + delta
            valid = np.all((cand[:, 1:] >= 0) & (cand[:, 1:] < spatial), axis=1)
            qk = flatten_coords(cand, spatial)
            pos = np.clip(np.searchsorted(skeys, qk), 0, max(n - 1, 0))
            found = valid if n == 0 else (skeys[pos] == qk) & valid
            in_idx = order[pos[found]]
            out_idx = np.nonzero(found)[0]
            pairs.append((in_idx.astype(np.int32), out_idx.astype(np.int32)))
        return coords, [int(s) for s in spatial], pairs

    numer = spatial + 2 * padding - dilation * (kernel - 1) - 1
    if ceil_mode:
        numer = numer + stride - 1  # partial edge windows produce outputs
    out_spatial = numer // stride + 1
    if ceil_mode:
        # reference clamp: the last window must start inside the input or
        # its LEFT padding — drop outputs starting in the right-pad region
        out_spatial = np.where((out_spatial - 1) * stride >= spatial + padding,
                               out_spatial - 1, out_spatial)
    cand = []
    for off in offsets:
        num = coords[:, 1:] + padding - off * dilation
        ok = np.all(num % stride == 0, axis=1) & np.all(num >= 0, axis=1)
        oc = num // stride
        ok &= np.all(oc < out_spatial, axis=1)
        cand.append((ok, oc))
    keyed = [flatten_coords(
        np.concatenate([coords[ok, :1], oc[ok]], axis=1), out_spatial)
        for ok, oc in cand]
    allk = np.concatenate(keyed) if keyed else np.zeros(0, np.int64)
    uniq = np.unique(allk)
    out_coords = decode_keys(uniq, out_spatial)
    pairs = []
    for (ok, oc), qk in zip(cand, keyed):
        in_idx = np.nonzero(ok)[0]
        out_idx = np.searchsorted(uniq, qk)
        pairs.append((in_idx.astype(np.int32), out_idx.astype(np.int32)))
    return out_coords, [int(s) for s in out_spatial], pairs
