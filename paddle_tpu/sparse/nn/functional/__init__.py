"""paddle.sparse.nn.functional (ref: python/paddle/sparse/nn/functional/).

Layout conventions follow the reference: a sparse activation tensor has
shape [N, *spatial, C] (channels last), indices [1+nd, nnz], values
[nnz, C]; conv weights are kernel_size + [C_in, C_out].

All value computations route through dispatch.apply so the eager autograd
tape records them — sparse conv/pool/attention are trainable end to end.
The kernel map (which input point hits which output point under which
kernel offset) is host-side numpy; the per-offset compute is a gather ->
dense GEMM (MXU-friendly) -> segment scatter executed by XLA.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ....tensor_impl import Tensor, as_tensor_data, wrap
from ....dispatch import apply
from ... import SparseCooTensor, SparseCsrTensor
from .._kernel_map import build_kernel_map


def _np_coords(sp):
    return np.asarray(jax.device_get(as_tensor_data(sp.indices))).T.astype(np.int64)


def _coo_with_tensor_values(indices, values, shape):
    """Build a SparseCooTensor keeping `values` as a (possibly taped) Tensor
    so gradients flow across chained sparse.nn layers."""
    sp = SparseCooTensor.__new__(SparseCooTensor)
    sp.indices = jnp.asarray(as_tensor_data(indices)).astype(jnp.int64)
    sp.values = values
    sp.shape = list(shape)
    return sp


def _csr_with_tensor_values(crows, cols, values, shape):
    sp = SparseCsrTensor.__new__(SparseCsrTensor)
    sp.crows = jnp.asarray(as_tensor_data(crows)).astype(jnp.int64)
    sp.cols = jnp.asarray(as_tensor_data(cols)).astype(jnp.int64)
    sp.values = values
    sp.shape = list(shape)
    return sp


def _values_input(sp):
    """The values leaf as fed to dispatch.apply (keeps a live tape if any)."""
    return sp.values if isinstance(sp.values, Tensor) else jnp.asarray(sp.values)


# Rulebook cache (reference `key=` semantics, ref sparse/nn/layer/conv.py):
# building the kernel map costs a device->host indices sync plus numpy
# hashing; the sparsity pattern is identical across submanifold chains and
# across layers sharing a key, so cache per tensor (propagated through subm
# outputs) and per user key. A keyed hit additionally requires the SAME
# indices array object — a reused key with a different point cloud must
# rebuild, never return a stale map.
_RULEBOOK_CACHE = {}
_RULEBOOK_CACHE_MAX = 256


def _get_kernel_map(x, kernel, stride, padding, dilation, subm, key=None,
                    ceil_mode=False):
    nd = len(kernel)
    # the kernel map is channel-independent: key on batch+spatial dims only,
    # so subm chains that change channel width still hit the propagated cache
    geom = (kernel, stride, padding, dilation, subm, ceil_mode,
            tuple(x.shape[:1 + nd]))
    if key is not None:
        cached = _RULEBOOK_CACHE.get((key, geom))
        if cached is not None and cached[0] is x.indices:
            return cached[1]
    per_tensor = getattr(x, "_kmap_cache", None)
    if per_tensor is None:
        per_tensor = x._kmap_cache = {}
    entry = per_tensor.get(geom)
    if entry is None:
        coords = _np_coords(x)
        out_coords, out_spatial, pairs = build_kernel_map(
            coords, x.shape[1:1 + nd], kernel, stride, padding, dilation,
            subm, ceil_mode)
        entry = {
            "out_coords": out_coords,
            "out_spatial": out_spatial,
            "pairs": pairs,
            "pairs_dev": tuple((jnp.asarray(i), jnp.asarray(j))
                               for i, j in pairs if len(i) > 0),
            "live": tuple(k for k, (i, j) in enumerate(pairs)
                          if len(i) > 0),
        }
        per_tensor[geom] = entry
    if key is not None:
        while len(_RULEBOOK_CACHE) >= _RULEBOOK_CACHE_MAX:
            _RULEBOOK_CACHE.pop(next(iter(_RULEBOOK_CACHE)))
        _RULEBOOK_CACHE[(key, geom)] = (x.indices, entry)
    return entry


def _conv(x, weight, bias, stride, padding, dilation, groups, subm, nd, name,
          key=None):
    assert isinstance(x, SparseCooTensor), f"{name} expects a SparseCooTensor"
    assert groups == 1, f"{name}: groups > 1 not supported"

    def tup(v):
        return (v,) * nd if isinstance(v, int) else tuple(v)

    w_data = as_tensor_data(weight)
    kernel = tuple(int(k) for k in w_data.shape[:nd])
    cin, cout = int(w_data.shape[nd]), int(w_data.shape[nd + 1])
    assert x.shape[1 + nd] == cin, (x.shape, w_data.shape)

    entry = _get_kernel_map(x, kernel, tup(stride), tup(padding),
                            tup(dilation), subm, key=key)
    out_coords, out_spatial = entry["out_coords"], entry["out_spatial"]
    pairs_dev, live = entry["pairs_dev"], entry["live"]
    n_out = out_coords.shape[0]

    def compute(values, w, *maybe_bias):
        wk = w.reshape((-1, cin, cout))
        out = jnp.zeros((n_out, cout), values.dtype)
        for k, (ii, jj) in zip(live, pairs_dev):
            out = out.at[jj].add(values[ii] @ wk[k])
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    args = (_values_input(x), weight) + ((bias,) if bias is not None else ())
    out_vals = apply(compute, *args, op_name=name)
    new_shape = [x.shape[0]] + list(out_spatial) + [cout]
    out = _coo_with_tensor_values(
        x.indices if subm else jnp.asarray(out_coords.T), out_vals, new_shape)
    if subm:
        # identical coords -> later subm layers reuse this rulebook cache
        out._kmap_cache = x._kmap_cache
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse 3-D conv (ref sparse/nn/functional/conv.py conv3d)."""
    assert data_format == "NDHWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=False, nd=3, name="sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv: output coords == input coords
    (ref sparse/nn/functional/conv.py subm_conv3d)."""
    assert data_format == "NDHWC"
    return _conv(x, weight, bias, 1, padding, dilation, groups,
                 subm=True, nd=3, name="subm_conv3d", key=key)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    assert data_format == "NHWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=False, nd=2, name="sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    assert data_format == "NHWC"
    return _conv(x, weight, bias, 1, padding, dilation, groups,
                 subm=True, nd=2, name="subm_conv2d", key=key)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse max pooling (ref sparse/nn/functional/pooling.py)."""
    assert isinstance(x, SparseCooTensor) and data_format == "NDHWC"
    nd = 3

    def tup(v):
        return (v,) * nd if isinstance(v, int) else tuple(v)

    kernel = tup(kernel_size)
    stride = tup(stride if stride is not None else kernel_size)
    entry = _get_kernel_map(x, kernel, stride, tup(padding), tup(1),
                            subm=False, ceil_mode=ceil_mode)
    out_coords, out_spatial = entry["out_coords"], entry["out_spatial"]
    n_out = out_coords.shape[0]
    if "pool_cat" not in entry:
        pairs = entry["pairs"]
        entry["pool_cat"] = (
            jnp.asarray(np.concatenate([i for i, _ in pairs])),
            jnp.asarray(np.concatenate([j for _, j in pairs])))
    in_cat, out_cat = entry["pool_cat"]

    def compute(values):
        return jax.ops.segment_max(values[in_cat], out_cat,
                                   num_segments=n_out)

    out_vals = apply(compute, _values_input(x), op_name="sparse_max_pool3d")
    new_shape = [x.shape[0]] + list(out_spatial) + [x.shape[-1]]
    return _coo_with_tensor_values(jnp.asarray(out_coords.T), out_vals,
                                   new_shape)


def _values_unary(fn, op_name):
    def op(x, *args, **kw):
        if isinstance(x, SparseCsrTensor):
            out = apply(fn, _values_input(x), op_name=op_name)
            return _csr_with_tensor_values(x.crows, x.cols, out, x.shape)
        if isinstance(x, SparseCooTensor):
            out = apply(fn, _values_input(x), op_name=op_name)
            return _coo_with_tensor_values(x.indices, out, x.shape)
        return apply(fn, x, op_name=op_name)
    return op


relu = _values_unary(lambda v: jnp.maximum(v, 0), "sparse_relu")
relu6 = _values_unary(lambda v: jnp.clip(v, 0, 6), "sparse_relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _values_unary(
        lambda v: jnp.where(v >= 0, v, negative_slope * v), "sparse_leaky_relu")(x)


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored values only (the reference treats
    absent entries as -inf, ref sparse/nn/functional/activation.py)."""
    assert axis in (-1, None) or axis == len(x.shape) - 1, \
        "sparse softmax supports the last axis"
    csr = isinstance(x, SparseCsrTensor)
    coo = x.to_coo() if csr else x
    rows_np = _np_coords(coo)[:, :-1]
    # flatten every dim but the last into a row id
    row_id = np.zeros(rows_np.shape[0], np.int64)
    for d in range(rows_np.shape[1]):
        row_id = row_id * int(x.shape[d]) + rows_np[:, d]
    _, row_id = np.unique(row_id, return_inverse=True)
    n_rows = int(row_id.max()) + 1 if row_id.size else 0
    rid = jnp.asarray(row_id)

    def compute(values):
        m = jax.ops.segment_max(values, rid, num_segments=n_rows)
        p = jnp.exp(values - m[rid])
        z = jax.ops.segment_sum(p, rid, num_segments=n_rows)
        return p / z[rid]

    # to_coo strips any taped values, but keeps row-major value ORDER — feed
    # the original tensor's values so the tape survives for CSR inputs too.
    out = apply(compute, _values_input(x), op_name="sparse_softmax")
    if csr:
        return _csr_with_tensor_values(x.crows, x.cols, out, x.shape)
    return _coo_with_tensor_values(coo.indices, out, x.shape)


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NDHWC",
               use_global_stats=None, name=None):
    """BatchNorm over sparse values [nnz, C] (ref sparse/nn/layer/norm.py —
    the reference also normalizes the values view with dense BN)."""
    from ....nn import functional as F
    vals = x.values if isinstance(x.values, Tensor) else wrap(x.values)
    out = F.batch_norm(vals, running_mean, running_var, weight, bias,
                       training=training, momentum=momentum, epsilon=epsilon,
                       data_format="NC", use_global_stats=use_global_stats)
    return _coo_with_tensor_values(x.indices, out, x.shape)


sync_batch_norm = batch_norm


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse attention: scores evaluated ONLY at sparse_mask's nnz
    coordinates (SDDMM), sparse row softmax, then SpMM with value
    (ref python/paddle/sparse/nn/functional/transformer.py attention).

    query/key/value: dense [B, H, S, D]. sparse_mask: 2-D [S, S] COO/CSR
    layout shared across batch and heads (the reference takes a batched CSR;
    a shared layout is the common case and the TPU-friendly one — one
    kernel map, batched GEMMs). key_padding_mask [B, S] and attn_mask
    [S, S] are additive (-inf to exclude), as in the reference.
    """
    coo = sparse_mask.to_coo() if isinstance(sparse_mask, SparseCsrTensor) \
        else sparse_mask
    assert coo.indices.shape[0] == 2, "sparse_mask must be 2-D [S, S]"
    idx = np.asarray(jax.device_get(coo.indices))
    rows, cols = jnp.asarray(idx[0]), jnp.asarray(idx[1])
    S = int(coo.shape[0])

    q_in = query if isinstance(query, Tensor) else wrap(query)
    extra = []
    has_kpm = key_padding_mask is not None
    has_am = attn_mask is not None
    if has_kpm:
        extra.append(key_padding_mask)
    if has_am:
        extra.append(attn_mask)

    def compute(q, k, v, *masks):
        D = q.shape[-1]
        qr = jnp.take(q, rows, axis=2)          # [B, H, nnz, D]
        kc = jnp.take(k, cols, axis=2)
        s = jnp.einsum("bhnd,bhnd->bhn", qr, kc) / math.sqrt(D)
        mi = 0
        if has_kpm:
            s = s + jnp.take(masks[mi], cols, axis=1)[:, None, :]
            mi += 1
        if has_am:
            s = s + masks[mi][rows, cols][None, None, :]
        B, H, nnz = s.shape
        flat = s.reshape(B * H, nnz)
        seg_max = jax.vmap(
            lambda t: jax.ops.segment_max(t, rows, num_segments=S))(flat)
        p = jnp.exp(flat - jnp.take(seg_max, rows, axis=1))
        z = jax.vmap(
            lambda t: jax.ops.segment_sum(t, rows, num_segments=S))(p)
        p = p / jnp.take(z, rows, axis=1)
        vc = jnp.take(v, cols, axis=2).reshape(B * H, nnz, D)
        out = jax.vmap(
            lambda pw, vv: jax.ops.segment_sum(pw[:, None] * vv, rows,
                                               num_segments=S))(p, vc)
        return out.reshape(B, H, S, D)

    return apply(compute, q_in, key, value, *extra, op_name="sparse_attention")
