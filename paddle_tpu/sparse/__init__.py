"""paddle.sparse subset: COO tensors (ref: python/paddle/sparse/*).

TPU/XLA has no native sparse kernels; COO ops lower to dense gathers/scatters
(segment_sum), which XLA tiles well for the moderate-nnz cases the reference's
sparse API targets. Layout: indices [ndim, nnz] int64 + values [nnz, ...].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor_impl import Tensor, as_tensor_data, wrap


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = jnp.asarray(as_tensor_data(indices)).astype(jnp.int64)
        self.values = jnp.asarray(as_tensor_data(values))
        self.shape = list(shape)

    @property
    def nnz(self):
        return int(self.indices.shape[1])

    def to_dense(self):
        dense = jnp.zeros(tuple(self.shape), self.values.dtype)
        idx = tuple(self.indices[i] for i in range(self.indices.shape[0]))
        return wrap(dense.at[idx].add(self.values))

    def numpy(self):
        return np.asarray(as_tensor_data(self.to_dense()))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.values.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, stop_gradient=True):
    ind = jnp.asarray(as_tensor_data(indices))
    val = jnp.asarray(as_tensor_data(values))
    if dtype is not None:
        val = val.astype(dtype)
    if shape is None:
        shape = [int(x) + 1 for x in np.asarray(ind.max(axis=1))]
    return SparseCooTensor(ind, val, shape)


def to_dense(sp):
    return sp.to_dense() if isinstance(sp, SparseCooTensor) else sp


def from_dense(x, name=None):
    arr = as_tensor_data(x)
    nz = jnp.nonzero(arr)  # host-side (eager only), like reference to_sparse_coo
    indices = jnp.stack(nz, axis=0)
    values = arr[nz]
    return SparseCooTensor(indices, values, arr.shape)


to_sparse_coo = from_dense


def matmul(a, b):
    """sparse @ dense → dense (ref sparse/binary.py matmul)."""
    bd = as_tensor_data(b) if not isinstance(b, SparseCooTensor) else as_tensor_data(b.to_dense())
    if isinstance(a, SparseCooTensor):
        assert a.indices.shape[0] == 2, "sparse matmul supports 2-D lhs"
        rows, cols = a.indices[0], a.indices[1]
        # gather rhs rows at col indices, scale, segment-sum into output rows
        contrib = a.values[:, None] * bd[cols]  # [nnz, n]
        out = jax.ops.segment_sum(contrib, rows, num_segments=a.shape[0])
        return wrap(out.astype(bd.dtype))
    return wrap(as_tensor_data(a) @ bd)


def add(a, b):
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        assert a.shape == b.shape
        indices = jnp.concatenate([a.indices, b.indices], axis=1)
        values = jnp.concatenate([a.values, b.values], axis=0)
        return SparseCooTensor(indices, values, a.shape)
    return wrap(as_tensor_data(to_dense(a)) + as_tensor_data(to_dense(b)))


def multiply(a, b):
    return wrap(as_tensor_data(to_dense(a)) * as_tensor_data(to_dense(b)))


def relu(a):
    if isinstance(a, SparseCooTensor):
        return SparseCooTensor(a.indices, jnp.maximum(a.values, 0), a.shape)
    return wrap(jnp.maximum(as_tensor_data(a), 0))
