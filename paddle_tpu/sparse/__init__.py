"""paddle.sparse subset: COO tensors (ref: python/paddle/sparse/*).

TPU/XLA has no native sparse kernels; COO ops lower to dense gathers/scatters
(segment_sum), which XLA tiles well for the moderate-nnz cases the reference's
sparse API targets. Layout: indices [ndim, nnz] int64 + values [nnz, ...].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor_impl import Tensor, as_tensor_data, wrap


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = jnp.asarray(as_tensor_data(indices)).astype(jnp.int64)
        self.values = jnp.asarray(as_tensor_data(values))
        self.shape = list(shape)

    @property
    def nnz(self):
        return int(self.indices.shape[1])

    def to_dense(self):
        dense = jnp.zeros(tuple(self.shape), self.values.dtype)
        idx = tuple(self.indices[i] for i in range(self.indices.shape[0]))
        return wrap(dense.at[idx].add(self.values))

    def numpy(self):
        return np.asarray(as_tensor_data(self.to_dense()))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.values.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, stop_gradient=True):
    ind = jnp.asarray(as_tensor_data(indices))
    val = jnp.asarray(as_tensor_data(values))
    if dtype is not None:
        val = val.astype(dtype)
    if shape is None:
        shape = [int(x) + 1 for x in np.asarray(ind.max(axis=1))]
    return SparseCooTensor(ind, val, shape)


def to_dense(sp):
    return sp.to_dense() if isinstance(sp, SparseCooTensor) else sp


def from_dense(x, name=None):
    arr = as_tensor_data(x)
    nz = jnp.nonzero(arr)  # host-side (eager only), like reference to_sparse_coo
    indices = jnp.stack(nz, axis=0)
    values = arr[nz]
    return SparseCooTensor(indices, values, arr.shape)


to_sparse_coo = from_dense


def matmul(a, b):
    """sparse @ dense → dense (ref sparse/binary.py matmul)."""
    bd = as_tensor_data(b) if not isinstance(b, SparseCooTensor) else as_tensor_data(b.to_dense())
    if isinstance(a, SparseCooTensor):
        assert a.indices.shape[0] == 2, "sparse matmul supports 2-D lhs"
        rows, cols = a.indices[0], a.indices[1]
        # gather rhs rows at col indices, scale, segment-sum into output rows
        contrib = a.values[:, None] * bd[cols]  # [nnz, n]
        out = jax.ops.segment_sum(contrib, rows, num_segments=a.shape[0])
        return wrap(out.astype(bd.dtype))
    return wrap(as_tensor_data(a) @ bd)


def add(a, b):
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        assert a.shape == b.shape
        indices = jnp.concatenate([a.indices, b.indices], axis=1)
        values = jnp.concatenate([a.values, b.values], axis=0)
        return SparseCooTensor(indices, values, a.shape)
    return wrap(as_tensor_data(to_dense(a)) + as_tensor_data(to_dense(b)))


def multiply(a, b):
    return wrap(as_tensor_data(to_dense(a)) * as_tensor_data(to_dense(b)))


def relu(a):
    if isinstance(a, SparseCooTensor):
        return SparseCooTensor(a.indices, jnp.maximum(a.values, 0), a.shape)
    return wrap(jnp.maximum(as_tensor_data(a), 0))


class SparseCsrTensor:
    """CSR layout (ref sparse/creation.py sparse_csr_tensor): crows [m+1],
    cols [nnz], values [nnz]. Converted to COO for compute."""

    def __init__(self, crows, cols, values, shape):
        self.crows = jnp.asarray(as_tensor_data(crows)).astype(jnp.int64)
        self.cols = jnp.asarray(as_tensor_data(cols)).astype(jnp.int64)
        self.values = jnp.asarray(as_tensor_data(values))
        self.shape = list(shape)

    @property
    def nnz(self):
        return int(self.cols.shape[0])

    def to_coo(self):
        counts = jnp.diff(self.crows)
        rows = jnp.repeat(jnp.arange(len(counts), dtype=jnp.int64), counts,
                          total_repeat_length=self.nnz)
        return SparseCooTensor(jnp.stack([rows, self.cols]), self.values,
                               self.shape)

    def to_dense(self):
        return self.to_coo().to_dense()

    def numpy(self):
        return np.asarray(as_tensor_data(self.to_dense()))

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.values.dtype})")


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    val = jnp.asarray(as_tensor_data(values))
    if dtype is not None:
        val = val.astype(dtype)
    return SparseCsrTensor(crows, cols, val, shape)


def _valueswise(fn, zero_preserving=True):
    """Lift an elementwise fn to sparse tensors: zero-preserving ops act on
    stored values only (sparsity kept); others densify."""

    def op(x, *args, **kw):
        if isinstance(x, SparseCsrTensor):
            if zero_preserving:
                return SparseCsrTensor(x.crows, x.cols, fn(x.values, *args, **kw),
                                       x.shape)
            return wrap(fn(as_tensor_data(x.to_dense()), *args, **kw))
        if isinstance(x, SparseCooTensor):
            if zero_preserving:
                return SparseCooTensor(x.indices, fn(x.values, *args, **kw),
                                       x.shape)
            return wrap(fn(as_tensor_data(x.to_dense()), *args, **kw))
        return wrap(fn(as_tensor_data(x), *args, **kw))

    return op


sin = _valueswise(jnp.sin)
tan = _valueswise(jnp.tan)
asin = _valueswise(jnp.arcsin)
atan = _valueswise(jnp.arctan)
sinh = _valueswise(jnp.sinh)
tanh = _valueswise(jnp.tanh)
asinh = _valueswise(jnp.arcsinh)
atanh = _valueswise(jnp.arctanh)
sqrt = _valueswise(jnp.sqrt)
square = _valueswise(jnp.square)
log1p = _valueswise(jnp.log1p)
abs = _valueswise(jnp.abs)
neg = _valueswise(jnp.negative)
expm1 = _valueswise(jnp.expm1)
deg2rad = _valueswise(jnp.deg2rad)
rad2deg = _valueswise(jnp.rad2deg)
isnan = _valueswise(jnp.isnan)


def pow(x, factor, name=None):
    return _valueswise(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    if isinstance(x, SparseCooTensor):
        ind = x.indices.astype(index_dtype) if index_dtype else x.indices
        val = x.values.astype(value_dtype) if value_dtype else x.values
        return SparseCooTensor(ind, val, x.shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(
            x.crows.astype(index_dtype) if index_dtype else x.crows,
            x.cols.astype(index_dtype) if index_dtype else x.cols,
            x.values.astype(value_dtype) if value_dtype else x.values, x.shape)
    return wrap(as_tensor_data(x).astype(value_dtype))


def coalesce(x, name=None):
    """Merge duplicate coordinates (sum values), sort indices row-major."""
    assert isinstance(x, SparseCooTensor)
    flat = jnp.zeros((), jnp.int64)
    for d in range(x.indices.shape[0]):
        flat = flat * x.shape[d] + x.indices[d]
    order = jnp.argsort(flat)
    flat_s = flat[order]
    vals_s = x.values[order]
    uniq, inv = jnp.unique(flat_s, return_inverse=True, size=flat_s.shape[0],
                           fill_value=-1)
    summed = jax.ops.segment_sum(vals_s, inv, num_segments=uniq.shape[0])
    keep = np.asarray(jax.device_get(uniq)) >= 0
    uniq_np = np.asarray(jax.device_get(uniq))[keep]
    summed = jnp.asarray(np.asarray(jax.device_get(summed))[keep])
    coords = []
    rem = jnp.asarray(uniq_np)
    for d in reversed(range(len(x.shape))):
        coords.append(rem % x.shape[d])
        rem = rem // x.shape[d]
    indices = jnp.stack(list(reversed(coords)))
    return SparseCooTensor(indices, summed, x.shape)


def is_same_shape(x, y):
    xs = x.shape if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else \
        list(as_tensor_data(x).shape)
    ys = y.shape if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else \
        list(as_tensor_data(y).shape)
    return list(xs) == list(ys)


def reshape(x, shape, name=None):
    assert isinstance(x, SparseCooTensor)
    flat = jnp.zeros((), jnp.int64)
    for d in range(x.indices.shape[0]):
        flat = flat * x.shape[d] + x.indices[d]
    coords = []
    rem = flat
    for d in reversed(range(len(shape))):
        coords.append(rem % shape[d])
        rem = rem // shape[d]
    return SparseCooTensor(jnp.stack(list(reversed(coords))), x.values,
                           list(shape))


def transpose(x, perm, name=None):
    assert isinstance(x, SparseCooTensor)
    ind = jnp.stack([x.indices[p] for p in perm])
    return SparseCooTensor(ind, x.values, [x.shape[p] for p in perm])


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = as_tensor_data(to_dense(x))
    out = jnp.sum(d, axis=axis, keepdims=keepdim, dtype=dtype)
    return wrap(out)


def subtract(a, b, name=None):
    return wrap(as_tensor_data(to_dense(a)) - as_tensor_data(to_dense(b)))


def divide(a, b, name=None):
    return wrap(as_tensor_data(to_dense(a)) / as_tensor_data(to_dense(b)))


def mv(a, v, name=None):
    """sparse matrix @ dense vector."""
    vd = as_tensor_data(v)
    if isinstance(a, SparseCsrTensor):
        a = a.to_coo()
    if isinstance(a, SparseCooTensor):
        rows, cols = a.indices[0], a.indices[1]
        contrib = a.values * vd[cols]
        return wrap(jax.ops.segment_sum(contrib, rows, num_segments=a.shape[0]))
    return wrap(as_tensor_data(a) @ vd)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) with sparse x (ref sparse/binary.py)."""
    prod = as_tensor_data(matmul(x, y))
    return wrap(beta * as_tensor_data(to_dense(input)) + alpha * prod)


def masked_matmul(x, y, mask, name=None):
    """Dense @ dense evaluated only at mask's nnz coordinates (SDDMM)."""
    xd, yd = as_tensor_data(x), as_tensor_data(y)
    assert isinstance(mask, (SparseCooTensor, SparseCsrTensor))
    coo = mask.to_coo() if isinstance(mask, SparseCsrTensor) else mask
    rows, cols = coo.indices[0], coo.indices[1]
    vals = jnp.einsum("nd,nd->n", xd[rows, :], yd[:, cols].T)
    if isinstance(mask, SparseCsrTensor):
        return SparseCsrTensor(mask.crows, mask.cols, vals, mask.shape)
    return SparseCooTensor(coo.indices, vals, coo.shape)


def __getattr__(name):
    # Deferred: sparse.nn imports paddle_tpu.nn, which may not be fully
    # initialized while the top-level package is still importing. Use
    # importlib (NOT `from . import nn` — the fromlist machinery calls this
    # __getattr__ again and recurses).
    if name == "nn":
        import importlib
        return importlib.import_module(".nn", __name__)
    raise AttributeError(f"module 'paddle_tpu.sparse' has no attribute {name!r}")


def slice(x, axes, starts, ends, name=None):
    """Slice a sparse COO tensor along `axes` (ref sparse/unary.py slice):
    filter stored entries inside the range, shift coordinates."""
    assert isinstance(x, SparseCooTensor)
    ind = np.asarray(jax.device_get(x.indices))
    val = np.asarray(jax.device_get(x.values))
    new_shape = list(x.shape)
    keep = np.ones(ind.shape[1], bool)
    for ax, st, en in zip(axes, starts, ends):
        st = st + x.shape[ax] if st < 0 else st
        en = en + x.shape[ax] if en < 0 else min(en, x.shape[ax])
        keep &= (ind[ax] >= st) & (ind[ax] < en)
        new_shape[ax] = en - st
    ind = ind[:, keep].copy()
    for ax, st, _ in zip(axes, starts, ends):
        st = st + x.shape[ax] if st < 0 else st
        ind[ax] -= st
    return SparseCooTensor(jnp.asarray(ind), jnp.asarray(val[keep]), new_shape)
