"""QAT / PTQ passes: insert fake-quanters or observers, then convert to an
int8 deploy model.

Ref: python/paddle/quantization/quantize.py (Quantization base),
qat.py (QAT), ptq.py (PTQ). The pass structure mirrors the reference —
`_specify` annotates layers with their strategy, insert swaps layers via
the QAT layer mapping (or wraps them for observation), `convert` strips
the training scaffolding into int8-weight layers whose dequant multiply
XLA fuses into the MXU matmul/conv epilogue.
"""
from __future__ import annotations

import copy

from .. import nn
from ..nn.layer_base import Layer
from .qconfig import QuantConfig
from .qat_layers import (QuantedLinear, QuantedConv2D, ObserveWrapper,
                         QuantizedConv2D)


def _replace_sublayers(model: Layer, fn):
    """Depth-first sublayer replacement: fn(layer) -> new layer or None."""
    for name, layer in list(model._sub_layers.items()):
        new = fn(layer)
        if new is not None and new is not layer:
            model._sub_layers[name] = new
        else:
            _replace_sublayers(layer, fn)


class Quantization:
    """Base pass (ref quantize.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model, inplace=False):  # pragma: no cover - abstract
        raise NotImplementedError

    def convert(self, model, inplace=False):
        """Swap QAT/observer scaffolding for int8 deploy layers."""
        _model = model if inplace else copy.deepcopy(model)

        def conv(layer):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                from . import QuantizedLinear
                inner = (layer._linear if isinstance(layer, QuantedLinear)
                         else layer._conv)
                inner.weight = layer.weight
                inner.bias = layer.bias
                # quantize the LIVE weight along the axis training simulated
                # (a scale recorded before the last opt.step() would clip
                # channels that grew since)
                wq = layer.weight_quanter
                default_axis = 1 if isinstance(layer, QuantedLinear) else 0
                axis = wq.quant_axis() if wq is not None else default_axis
                cls = (QuantizedLinear if isinstance(layer, QuantedLinear)
                       else QuantizedConv2D)
                q = cls(inner, quant_axis=axis)
                if layer.activation_quanter is not None:
                    q.act_scale = layer.activation_quanter.scales()
                return q
            if isinstance(layer, ObserveWrapper):
                inner = layer._observed
                q = inner
                wo = layer.weight_observer
                w_scale = wo.scales() if wo is not None else None
                w_axis = wo.quant_axis() if wo is not None else 1
                if isinstance(inner, nn.Linear):
                    from . import QuantizedLinear
                    q = QuantizedLinear(inner, weight_scale=w_scale,
                                        quant_axis=w_axis)
                elif isinstance(inner, nn.Conv2D):
                    q = QuantizedConv2D(inner, weight_scale=w_scale,
                                        quant_axis=w_axis if wo is not None
                                        else 0)
                if layer.activation_observer is not None:
                    q.act_scale = layer.activation_observer.scales()
                return q
            return None

        _replace_sublayers(_model, conv)
        return _model


class QAT(Quantization):
    """Prepare a model for quantization-aware training (ref qat.py)."""

    def __init__(self, config: QuantConfig = None):
        if config is None:
            from .quanters import (QuanterFactory,
                                   FakeQuanterWithAbsMaxObserver,
                                   FakeQuanterChannelWiseAbsMax)
            config = QuantConfig(
                activation=QuanterFactory(FakeQuanterWithAbsMaxObserver,
                                          moving_rate=0.9),
                weight=QuanterFactory(FakeQuanterChannelWiseAbsMax,
                                      quant_axis=1))
            # conv weights are [out, in, kh, kw]: per-OUT-channel axis is 0
            config.add_type_config(
                nn.Conv2D,
                activation=QuanterFactory(FakeQuanterWithAbsMaxObserver,
                                          moving_rate=0.9),
                weight=QuanterFactory(FakeQuanterChannelWiseAbsMax,
                                      quant_axis=0))
        super().__init__(config)

    def quantize(self, model: Layer, inplace=False):
        _model = model if inplace else copy.deepcopy(model)
        self._config._specify(_model)
        mapping = self._config._qat_layer_mapping

        def ins(layer):
            if not self._config._needs_quant(layer):
                return None
            for src, dst in mapping.items():
                if type(layer) is src:
                    return dst(layer, layer._quant_config)
            return None

        _replace_sublayers(_model, ins)
        return _model


class PTQ(Quantization):
    """Post-training quantization: observe -> calibrate -> convert
    (ref ptq.py)."""

    def __init__(self, config: QuantConfig = None):
        if config is None:
            from .observers import (ObserverFactory, AbsmaxObserver,
                                    PerChannelAbsmaxObserver)
            config = QuantConfig(
                activation=ObserverFactory(AbsmaxObserver),
                weight=ObserverFactory(PerChannelAbsmaxObserver,
                                       quant_axis=1))
            # conv weights are [out, in, kh, kw]: per-OUT-channel axis is 0
            config.add_type_config(
                nn.Conv2D,
                activation=ObserverFactory(AbsmaxObserver),
                weight=ObserverFactory(PerChannelAbsmaxObserver,
                                       quant_axis=0))
        super().__init__(config)

    def quantize(self, model: Layer, inplace=False):
        _model = model if inplace else copy.deepcopy(model)
        self._config._specify(_model)

        def wrapit(layer):
            if not self._config._needs_quant(layer):
                return None
            if isinstance(layer, (nn.Linear, nn.Conv2D)):
                return ObserveWrapper(layer, layer._quant_config)
            return None

        _replace_sublayers(_model, wrapit)
        return _model
