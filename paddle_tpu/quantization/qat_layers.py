"""QAT wrapper layers + observer wrapper + converted (deploy) layers.

Ref: python/paddle/nn/quant/qat/ (QuantedLinear, QuantedConv2D),
python/paddle/quantization/wrapper.py (ObserveWrapper). A Quanted* layer
shares the wrapped layer's parameters and fake-quants weight/activation in
forward; `convert()` (see qat.py/ptq.py) swaps them for int8 deploy layers
whose dequant scale XLA fuses into the matmul/conv epilogue.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import nn
from ..nn.layer_base import Layer
from ..nn import functional as F
from ..tensor_impl import Tensor, as_tensor_data, wrap


def _make(factory, layer):
    return None if factory is None else factory._instance(layer)


class QuantedLinear(Layer):
    """QAT Linear: y = (fq_a(x)) @ fq_w(W) + b (ref nn/quant/qat linear)."""

    def __init__(self, linear, q_config):
        super().__init__()
        self._linear = linear
        self.weight = linear.weight
        self.bias = linear.bias
        self.weight_quanter = _make(q_config.weight, linear)
        self.activation_quanter = _make(q_config.activation, linear)

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    """QAT Conv2D (ref nn/quant/qat conv)."""

    def __init__(self, conv, q_config):
        super().__init__()
        self._conv = conv
        self.weight = conv.weight
        self.bias = conv.bias
        self.weight_quanter = _make(q_config.weight, conv)
        self.activation_quanter = _make(q_config.activation, conv)

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        c = self._conv
        return F.conv2d(x, w, self.bias, c._stride, c._padding, c._dilation,
                        c._groups, c._data_format)


class ObserveWrapper(Layer):
    """PTQ calibration wrapper: observe input activations, then run the
    wrapped layer unchanged (ref quantization/wrapper.py)."""

    def __init__(self, observed, q_config, observe_weight=True):
        super().__init__()
        self._observed = observed
        self.activation_observer = _make(q_config.activation, observed)
        self.weight_observer = (_make(q_config.weight, observed)
                                if observe_weight and
                                getattr(observed, "weight", None) is not None
                                else None)

    def forward(self, *args, **kwargs):
        if self.activation_observer is not None and args:
            self.activation_observer(args[0])
        if self.weight_observer is not None:
            self.weight_observer(self._observed.weight)
        return self._observed(*args, **kwargs)


# ---------------------------------------------------------------------------
# converted / deploy layers (int8 weights + static scales)
def quantize_with_scale(w, weight_scale, quant_axis):
    """int8-quantize w: with an explicit scale (broadcast to w.ndim along
    quant_axis), or computed per-channel (quant_axis >= 0) / per-tensor
    (quant_axis < 0) from the live weight."""
    w = as_tensor_data(w).astype(jnp.float32)
    if weight_scale is not None:
        scale = jnp.asarray(weight_scale, jnp.float32)
        if scale.ndim != w.ndim and scale.size > 1:
            shape = [1] * w.ndim
            shape[quant_axis] = -1
            scale = scale.reshape(shape)
    elif quant_axis is None or quant_axis < 0:
        scale = jnp.maximum(jnp.abs(w).max(), 1e-9) / 127.0
    else:
        reduce_axes = tuple(i for i in range(w.ndim) if i != quant_axis)
        amax = jnp.abs(w).max(axis=reduce_axes, keepdims=True)
        scale = jnp.maximum(amax, 1e-9) / 127.0
    q = jnp.clip(jnp.round(w / scale), -128, 127).astype(jnp.int8)
    return q, scale


class QuantizedConv2D(Layer):
    """int8-weight conv for deploy; dequant scale folds into the epilogue.
    Does NOT retain the fp32 source conv — only its int8 weight, scale,
    bias, and geometry survive conversion."""

    def __init__(self, conv, weight_scale=None, quant_axis=0):
        super().__init__()
        self.qweight, self.scale = quantize_with_scale(
            conv.weight, weight_scale, quant_axis)
        self.bias = conv.bias
        self._stride = conv._stride
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._data_format = conv._data_format

    def forward(self, x):
        w = self.qweight.astype(jnp.float32) * self.scale
        return F.conv2d(x, wrap(w), self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)
