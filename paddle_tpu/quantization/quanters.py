"""Fake quanters: simulate int quantization during QAT with a
straight-through gradient estimator.

Ref: python/paddle/quantization/base_quanter.py, quanters/abs_max.py
(FakeQuanterWithAbsMaxObserver). A quanter is a Layer whose forward
returns ``x + stop_gradient(dequant(quant(x)) - x)`` — the forward sees
quantized values, the backward passes through untouched (STE). All value
math runs through paddle ops so the eager tape records it; under
jit.to_static the same ops trace into XLA (with the scale frozen to its
calibrated value, since python-side EMA state cannot update in-graph).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..nn.layer_base import Layer
from ..tensor_impl import Tensor, as_tensor_data, wrap


class QuanterFactory:
    def __init__(self, cls=None, **kwargs):
        self._cls = cls
        self._kwargs = kwargs

    def _instance(self, layer=None):
        return self._cls(layer=layer, **self._kwargs)


def quanter(cls):
    """Decorator: make `Cls(**kw)` usable directly as a factory in
    QuantConfig (ref: quantization/factory.py `quanter`)."""
    def build(**kwargs):
        return QuanterFactory(cls, **kwargs)
    build._cls = cls
    return build


class BaseQuanter(Layer):
    def __init__(self, quant_bits=8, layer=None):
        super().__init__()
        self._quant_bits = quant_bits

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def zero_points(self):
        return 0.0

    def _qmax(self):
        return 2.0 ** (self._quant_bits - 1) - 1

    @staticmethod
    def _ste(x, scale, qmax):
        """x (Tensor or array) -> fake-quantized Tensor with STE grad."""
        t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        arr = t._data
        s = jnp.maximum(jnp.asarray(scale, arr.dtype), 1e-9)
        q = jnp.clip(jnp.round(arr / s), -qmax - 1, qmax) * s
        from ..dispatch import apply as _apply
        import jax
        return _apply(lambda a: a + jax.lax.stop_gradient(
            q.astype(a.dtype) - a), t, op_name="fake_quant")


def _is_tracer(a):
    import jax
    return isinstance(a, jax.core.Tracer)


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-average absmax fake quanter (ref quanters/abs_max.py
    FakeQuanterWithAbsMaxObserverLayer): in training, updates an EMA of the
    batch absmax then fake-quants with it; in eval, uses the stored EMA.
    Under jit tracing the host-side EMA cannot update: the calibrated scale
    is frozen into the graph (or, if never calibrated, computed in-graph
    from the live tensor)."""

    def __init__(self, moving_rate=0.9, quant_bits=8, layer=None):
        super().__init__(quant_bits, layer)
        self._rate = moving_rate
        self._state = None

    def forward(self, x):
        arr = as_tensor_data(x)
        if _is_tracer(arr):
            if self._state is not None:
                scale = max(self._state, 1e-9) / self._qmax()
            else:
                scale = jnp.maximum(jnp.abs(arr).max(), 1e-9) / self._qmax()
            return self._ste(x, scale, self._qmax())
        if self.training or self._state is None:
            cur = float(jnp.abs(arr).max())
            self._state = cur if self._state is None else (
                self._rate * self._state + (1 - self._rate) * cur)
        scale = max(self._state, 1e-9) / self._qmax()
        return self._ste(x, scale, self._qmax())

    def scales(self):
        return max(self._state if self._state is not None else 1e-9,
                   1e-9) / self._qmax()


class FakeQuanterChannelWiseAbsMax(BaseQuanter):
    """Per-channel absmax fake quanter for weights (ref
    quanters capability / channel-wise abs-max): the scale is recomputed
    from the live weight every forward, so QAT tracks weight updates."""

    def __init__(self, quant_axis=0, quant_bits=8, layer=None):
        super().__init__(quant_bits, layer)
        self._axis = quant_axis
        self._last_scale = None

    def quant_axis(self):
        return self._axis

    def forward(self, x):
        arr = as_tensor_data(x)
        reduce_axes = tuple(i for i in range(arr.ndim) if i != self._axis)
        amax = jnp.abs(arr).max(axis=reduce_axes, keepdims=True)
        scale = jnp.maximum(amax, 1e-9) / self._qmax()
        if not _is_tracer(arr):
            self._last_scale = np.asarray(scale)
        return self._ste(x, scale, self._qmax())

    def scales(self):
        return self._last_scale
