"""Observers: collect activation/weight statistics for calibration.

Ref: python/paddle/quantization/base_observer.py (BaseObserver),
observers/abs_max.py (AbsmaxObserver). Observers are Layers that pass
inputs through unchanged while recording range statistics; after
calibration `cal_thresholds()` finalizes, and `scales()` / `zero_points()`
feed the convert pass. TPU note: statistics live host-side (python
floats/ndarrays) — observation is an eager-mode calibration phase, the
quantized model that comes out of convert() is pure XLA.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..nn.layer_base import Layer
from ..tensor_impl import Tensor, as_tensor_data


class ObserverFactory:
    """Deferred constructor: holds (cls, kwargs); `_instance(layer)` builds
    the observer bound to a layer (ref: quantization/factory.py)."""

    def __init__(self, cls=None, **kwargs):
        self._cls = cls if cls is not None else getattr(self, "_CLS", None)
        self._kwargs = kwargs

    def _instance(self, layer=None):
        return self._cls(layer=layer, **self._kwargs)


class BaseObserver(Layer):
    """ref base_observer.py: forward observes + returns input unchanged."""

    def __init__(self, quant_bits=8, layer=None):
        super().__init__()
        self._quant_bits = quant_bits
        self._layer = layer

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1  # per-tensor

    def observe(self, x):  # pragma: no cover - abstract
        raise NotImplementedError

    def cal_thresholds(self):
        pass

    def scales(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def zero_points(self):
        return 0.0  # symmetric by default

    def forward(self, x):
        self.observe(x)
        return x


class AbsmaxObserver(BaseObserver):
    """Running max of |x| over all observed batches (per-tensor symmetric),
    ref observers/abs_max.py."""

    def __init__(self, quant_bits=8, layer=None):
        super().__init__(quant_bits, layer)
        self._max = 1e-9

    def observe(self, x):
        self._max = max(self._max,
                        float(jnp.abs(as_tensor_data(x)).max()))

    def scales(self):
        return self._max / (2.0 ** (self._quant_bits - 1) - 1)

    @classmethod
    def factory(cls, **kw):
        return ObserverFactory(cls, **kw)


class MovingAverageAbsmaxObserver(BaseObserver):
    """EMA of per-batch absmax (the PTQ counterpart of the reference's
    moving-average quanter state)."""

    def __init__(self, moving_rate=0.9, quant_bits=8, layer=None):
        super().__init__(quant_bits, layer)
        self._rate = moving_rate
        self._state = None

    def observe(self, x):
        cur = float(jnp.abs(as_tensor_data(x)).max())
        self._state = cur if self._state is None else (
            self._rate * self._state + (1 - self._rate) * cur)

    def scales(self):
        s = self._state if self._state is not None else 1e-9
        return max(s, 1e-9) / (2.0 ** (self._quant_bits - 1) - 1)


class PercentileObserver(BaseObserver):
    """Clip range = the given percentile of |x| over everything observed
    (ref: PTQ percentile/hist observers). Where absmax lets one outlier
    blow up the scale — and with it the quantization error of every
    normal value — percentile trades a bounded clip of the outlier tail
    for a much finer grid. The serving KV calibration
    (``serving.quant.kv_ranges(observer_factory=...)``) uses this to clip
    activation outliers out of the per-page scales. Samples are
    reservoir-downsampled host-side to ``max_samples``."""

    def __init__(self, percentile=99.9, quant_bits=8, max_samples=1 << 20,
                 layer=None):
        super().__init__(quant_bits, layer)
        if not 0 < percentile <= 100:
            raise ValueError(f"percentile must be in (0, 100], got "
                             f"{percentile}")
        self._percentile = float(percentile)
        self._max_samples = int(max_samples)
        self._samples = []
        self._n_seen = 0
        self._threshold = None

    def observe(self, x):
        a = np.abs(np.asarray(as_tensor_data(x), np.float32)).ravel()
        self._n_seen += a.size
        if a.size > self._max_samples:
            # deterministic stride downsample: unbiased enough for a
            # range statistic, reproducible across runs
            a = a[:: a.size // self._max_samples + 1]
        self._samples.append(a)
        total = sum(s.size for s in self._samples)
        if total > self._max_samples:
            # cap the TOTAL retained across calls, not just each batch —
            # a long calibration loop must stay bounded-memory
            allv = np.concatenate(self._samples)
            self._samples = [allv[:: allv.size // self._max_samples + 1]]
        self._threshold = None

    def cal_thresholds(self):
        if not self._samples:
            self._threshold = 1e-9
            return
        allv = np.concatenate(self._samples)
        self._threshold = max(float(np.percentile(allv, self._percentile)),
                              1e-9)

    def scales(self):
        if self._threshold is None:
            self.cal_thresholds()
        return self._threshold / (2.0 ** (self._quant_bits - 1) - 1)


class PerChannelAbsmaxObserver(BaseObserver):
    """Per-channel |x| max along `quant_axis` (weights), ref channel-wise
    abs-max observer capability."""

    def __init__(self, quant_axis=0, quant_bits=8, layer=None):
        super().__init__(quant_bits, layer)
        self._axis = quant_axis
        self._max = None

    def quant_axis(self):
        return self._axis

    def observe(self, x):
        arr = as_tensor_data(x)
        reduce_axes = tuple(i for i in range(arr.ndim) if i != self._axis)
        cur = np.asarray(jnp.abs(arr).max(axis=reduce_axes))
        self._max = cur if self._max is None else np.maximum(self._max, cur)

    def scales(self):
        m = np.maximum(self._max, 1e-9)
        return m / (2.0 ** (self._quant_bits - 1) - 1)
