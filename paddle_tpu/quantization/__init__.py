"""Quantization subset (ref: python/paddle/quantization/*).

Weight-only int8 PTQ for TPU serving: per-channel symmetric int8 weights with
fp dequant-scale fused into the matmul epilogue by XLA. Also fake-quant
QAT modules (quant in forward, straight-through grad).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor_impl import Tensor, as_tensor_data, wrap
from .. import nn
from ..nn.layer_base import Layer


def abs_max_scale(w, axis=None):
    """Per-tensor or per-channel absmax scale → int8 range."""
    a = jnp.abs(as_tensor_data(w))
    amax = a.max() if axis is None else a.max(axis=axis, keepdims=True)
    return jnp.maximum(amax, 1e-8) / 127.0


def quantize_weight(w, axis=0):
    """Returns (int8 weight, fp32 scale); per-out-channel symmetric."""
    arr = as_tensor_data(w).astype(jnp.float32)
    reduce_axis = tuple(i for i in range(arr.ndim) if i != axis)
    scale = jnp.maximum(jnp.abs(arr).max(axis=reduce_axis, keepdims=True), 1e-8) / 127.0
    q = jnp.clip(jnp.round(arr / scale), -128, 127).astype(jnp.int8)
    return q, scale


def dequantize_weight(q, scale):
    return q.astype(jnp.float32) * scale


def fake_quant(x, bits=8):
    """Fake-quant with straight-through estimator (QAT forward):
    forward sees quantized values, gradient passes through unchanged."""
    import jax
    arr = as_tensor_data(x)
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.abs(arr).max(), 1e-8) / qmax
    q = jnp.clip(jnp.round(arr / scale), -qmax - 1, qmax) * scale
    return wrap(arr + jax.lax.stop_gradient(q - arr))


class QuantizedLinear(Layer):
    """Weight-only int8 linear for inference (ref incubate weight_only_linear).

    Stores int8 weight + per-channel scale; dequantizes in-graph so XLA fuses
    the scale multiply into the MXU matmul epilogue."""

    def __init__(self, linear_or_in, out_features=None, weight_scale=None,
                 quant_axis=1):
        super().__init__()
        if isinstance(linear_or_in, Layer):
            lin = linear_or_in
            w = lin.weight._data
            self.bias = lin.bias
        else:
            w = jnp.zeros((linear_or_in, out_features), jnp.float32)
            self.bias = None
        from .qat_layers import quantize_with_scale
        # default: per-out-channel on [in, out] (axis 1)
        self.qweight, self.scale = quantize_with_scale(
            w, weight_scale, quant_axis)

    def forward(self, x):
        w = dequantize_weight(self.qweight, self.scale)
        arr = as_tensor_data(x)
        out = arr @ w.astype(arr.dtype)
        if self.bias is not None:
            out = out + as_tensor_data(self.bias)
        return wrap(out)


# full observer/quanter/config QAT+PTQ framework (ref quantization/*)
from .observers import (ObserverFactory, BaseObserver, AbsmaxObserver,  # noqa: E402
                        MovingAverageAbsmaxObserver, PerChannelAbsmaxObserver,
                        PercentileObserver)
from .quanters import (QuanterFactory, quanter, BaseQuanter,  # noqa: E402
                       FakeQuanterWithAbsMaxObserver,
                       FakeQuanterChannelWiseAbsMax)
from .qconfig import (QuantConfig, SingleLayerConfig,  # noqa: E402
                      DEFAULT_QAT_LAYER_MAPPINGS)
from .qat_layers import (QuantedLinear, QuantedConv2D, ObserveWrapper,  # noqa: E402
                         QuantizedConv2D)
from .quantize import Quantization, QAT, PTQ  # noqa: E402


def quanted_model_size_bytes(model):
    """Report quantized parameter footprint (int8 weights count 1 byte;
    every other parameter counts once at its dtype width)."""
    from .qat_layers import QuantizedConv2D
    total = 0
    seen = set()
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, (QuantizedLinear, QuantizedConv2D)):
            total += int(np.prod(layer.qweight.shape))
            total += int(np.prod(layer.scale.shape)) * 4
        for p in layer.parameters(include_sublayers=False):
            if id(p) in seen:
                continue
            seen.add(id(p))
            total += int(np.prod(p.shape)) * jnp.dtype(p.dtype).itemsize
    return total
