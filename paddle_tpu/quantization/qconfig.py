"""QuantConfig: map layers to quantization strategies.

Ref: python/paddle/quantization/config.py — global config plus
by-layer / by-name-prefix / by-type overrides, a QAT layer mapping
(Linear -> QuantedLinear, Conv2D -> QuantedConv2D), and `_specify`
which walks the model annotating each layer with its SingleLayerConfig.
"""
from __future__ import annotations

from .. import nn


class SingleLayerConfig:
    """ref config.py SingleLayerConfig: (activation factory, weight factory)."""

    def __init__(self, activation, weight):
        self._activation = activation
        self._weight = weight

    @property
    def activation(self):
        return self._activation

    @property
    def weight(self):
        return self._weight

    def __str__(self):
        return f"activation: {self._activation}\nweight: {self._weight}"


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_config = (SingleLayerConfig(activation, weight)
                               if (activation is not None or
                                   weight is not None) else None)
        self._layer2config = {}
        self._prefix2config = {}
        self._type2config = {}
        self._qat_layer_mapping = {
            k: v for k, v in DEFAULT_QAT_LAYER_MAPPINGS.items()}
        self._customized_leaves = []

    # -- strategy setters (ref config.py API names) -------------------------
    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for lyr in layers:
            self._layer2config[id(lyr)] = SingleLayerConfig(activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = (layer_name if isinstance(layer_name, (list, tuple))
                 else [layer_name])
        for n in names:
            self._prefix2config[n] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type2config[t] = SingleLayerConfig(activation, weight)

    def add_qat_layer_mapping(self, source, target):
        self._qat_layer_mapping[source] = target

    def add_customized_leaf(self, layer_type):
        self._customized_leaves.append(layer_type)

    @property
    def customized_leaves(self):
        return self._customized_leaves

    # -- resolution ---------------------------------------------------------
    def _config_for(self, name, layer):
        if id(layer) in self._layer2config:
            return self._layer2config[id(layer)]
        for prefix, cfg in self._prefix2config.items():
            if name == prefix or name.startswith(prefix + "."):
                return cfg
        for t, cfg in self._type2config.items():
            if isinstance(layer, t):
                return cfg
        return self._global_config

    def _specify(self, model):
        """Annotate every sublayer with its resolved config
        (ref config.py _specify)."""
        for name, layer in model.named_sublayers(include_self=True):
            layer._quant_config = self._config_for(name, layer)

    def _needs_quant(self, layer):
        cfg = getattr(layer, "_quant_config", None)
        return cfg is not None and (cfg.activation is not None or
                                    cfg.weight is not None)


def _default_mappings():
    from .qat_layers import QuantedLinear, QuantedConv2D
    return {nn.Linear: QuantedLinear, nn.Conv2D: QuantedConv2D}


class _LazyMapping(dict):
    """DEFAULT_QAT_LAYER_MAPPINGS without a circular import at module load."""

    def __init__(self):
        super().__init__()
        self._loaded = False

    def _ensure(self):
        if not self._loaded:
            self.update(_default_mappings())
            self._loaded = True

    def items(self):
        self._ensure()
        return super().items()

    def __iter__(self):
        self._ensure()
        return super().__iter__()

    def __getitem__(self, k):
        self._ensure()
        return super().__getitem__(k)

    def get(self, k, default=None):
        self._ensure()
        return super().get(k, default)


DEFAULT_QAT_LAYER_MAPPINGS = _LazyMapping()
