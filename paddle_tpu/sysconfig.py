"""Build/system configuration (ref: python/paddle/sysconfig.py)."""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory containing the native extension headers."""
    return os.path.join(os.path.dirname(__file__), os.pardir, "native")


def get_lib():
    """Directory containing the compiled native runtime library."""
    return os.path.join(os.path.dirname(__file__), os.pardir, "native", "build")
