"""Discrete Fourier transforms (ref: python/paddle/fft.py).

TPU-native: every transform lowers to XLA's FFT HLO via jnp.fft (single fused
kernel per call, differentiable, jit-compatible). The Hermitian family is
expressed through the conjugate/swapped-norm identities (hfftn == irfftn of
the conjugate with the normalization direction swapped) rather than dedicated
kernels — same math, fewer primitives.

Norm conventions match the reference: "backward" (default), "ortho",
"forward".
"""
from __future__ import annotations

import jax.numpy as jnp

from .dispatch import apply
from .tensor_impl import as_tensor_data

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be forward, backward or ortho")
    return norm


def _swap_norm(norm):
    """Invert the normalization direction (used by the Hermitian family)."""
    return {"backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]


# -- standard complex transforms -------------------------------------------

def fft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.fft(a, n=n, axis=axis, norm=norm), x)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.ifft(a, n=n, axis=axis, norm=norm), x)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.fft2(a, s=s, axes=axes, norm=norm), x)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.ifft2(a, s=s, axes=axes, norm=norm), x)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.fftn(a, s=s, axes=axes, norm=norm), x)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.ifftn(a, s=s, axes=axes, norm=norm), x)


# -- real input -------------------------------------------------------------

def rfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.rfft(a, n=n, axis=axis, norm=norm), x)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.irfft(a, n=n, axis=axis, norm=norm), x)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.rfft2(a, s=s, axes=axes, norm=norm), x)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.irfft2(a, s=s, axes=axes, norm=norm), x)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.rfftn(a, s=s, axes=axes, norm=norm), x)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.irfftn(a, s=s, axes=axes, norm=norm), x)


# -- Hermitian input (real spectrum) ---------------------------------------

def hfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.hfft(a, n=n, axis=axis, norm=norm), x)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply(lambda a: jnp.fft.ihfft(a, n=n, axis=axis, norm=norm), x)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """n-D FFT of a Hermitian-symmetric signal (real output).

    Identity: hfftn(x) == irfftn(conj(x)) with the norm direction swapped.
    """
    _check_norm(norm)
    return apply(
        lambda a: jnp.fft.irfftn(jnp.conj(a), s=s, axes=axes,
                                 norm=_swap_norm(norm)), x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply(
        lambda a: jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes,
                                         norm=_swap_norm(norm))), x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


# -- helpers ----------------------------------------------------------------

def fftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor_impl import Tensor
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        out = out.astype(dtype)
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor_impl import Tensor
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        out = out.astype(dtype)
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x)
