"""Random ops (ref: python/paddle/tensor/random.py).

All draws consume keys from the seeded global generator
(paddle_tpu.framework.random); inside functional traces the keys derive from
the traced base key, keeping jit'd programs pure and reproducible.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data
from ..framework.random import next_key
from ..framework.state import get_default_dtype, to_jnp_dtype
from .creation import _shape, _norm_dtype


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    d = _norm_dtype(dtype, get_default_dtype())
    return Tensor(jax.random.normal(next_key(), _shape(shape), dtype=d))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor_data(mean)
        s = as_tensor_data(std)
        out_shape = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ()))
        return Tensor(m + s * jax.random.normal(next_key(), out_shape, dtype=get_default_dtype()))
    d = get_default_dtype()
    return Tensor(mean + std * jax.random.normal(next_key(), _shape(shape or [1]), dtype=d))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = _norm_dtype(dtype, get_default_dtype())
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=d,
                                     minval=as_tensor_data(min), maxval=as_tensor_data(max)))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = _norm_dtype(dtype, jnp.int64)
    return Tensor(jax.random.randint(next_key(), _shape(shape), int(low), int(high), dtype=d))


def randint_like(x, low=0, high=None, dtype=None):
    a = as_tensor_data(x)
    return randint(low, high, a.shape, dtype or a.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(to_jnp_dtype(dtype)))


def shuffle(x, axis=0):
    a = as_tensor_data(x)
    return Tensor(jax.random.permutation(next_key(), a, axis=axis, independent=False))


def multinomial(x, num_samples=1, replacement=False, name=None):
    a = as_tensor_data(x)
    logits = jnp.log(jnp.maximum(a, 1e-30))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(*a.shape[:-1], int(num_samples)))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(next_key(), a.shape, dtype=logits.dtype)
        _, out = jax.lax.top_k(logits + g, int(num_samples))
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    a = as_tensor_data(x)
    return Tensor(jax.random.bernoulli(next_key(), a).astype(a.dtype))


def poisson(x, name=None):
    a = as_tensor_data(x)
    return Tensor(jax.random.poisson(next_key(), a, dtype=jnp.int64).astype(a.dtype))


def exponential_(x, lam=1.0):
    a = as_tensor_data(x)
    out = jax.random.exponential(next_key(), a.shape, dtype=a.dtype) / lam
    if isinstance(x, Tensor):
        # random fill severs any autograd history: the new value does not
        # derive from the old one, so the stale node must not survive
        x._data = out
        x._node = None
        x._out_idx = 0
        return x
    return Tensor(out)


def normal_(x, mean=0.0, std=1.0):
    a = as_tensor_data(x)
    out = mean + std * jax.random.normal(next_key(), a.shape, dtype=a.dtype)
    if isinstance(x, Tensor):
        # random fill severs any autograd history: the new value does not
        # derive from the old one, so the stale node must not survive
        x._data = out
        x._node = None
        x._out_idx = 0
        return x
    return Tensor(out)


def uniform_(x, min=-1.0, max=1.0):
    a = as_tensor_data(x)
    out = jax.random.uniform(next_key(), a.shape, dtype=a.dtype, minval=min, maxval=max)
    if isinstance(x, Tensor):
        # random fill severs any autograd history: the new value does not
        # derive from the old one, so the stale node must not survive
        x._data = out
        x._node = None
        x._out_idx = 0
        return x
    return Tensor(out)
