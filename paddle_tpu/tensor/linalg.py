"""Linear algebra ops (ref: python/paddle/tensor/linalg.py, einsum.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply
from .math import _ax, matmul, mm, bmm, mv, dot  # noqa: F401  (re-export surface)


def t(x, name=None):
    def f(a):
        if a.ndim < 2:
            return a
        if a.ndim == 2:
            return a.T
        raise ValueError("paddle.t only supports ndim<=2; use transpose")
    return _apply(f, x, op_name="t")


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        ax = _ax(axis)
        if p == "fro" or (p == 2 and ax is None):
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p == "nuc":
            s = jnp.linalg.svd(a, compute_uv=False)
            return jnp.sum(s, axis=-1, keepdims=keepdim)
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=ax, keepdims=keepdim), 1.0 / p)
    return _apply(f, x, op_name="norm")


def vector_norm(x, p=2, axis=None, keepdim=False):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    def f(a):
        return jnp.linalg.norm(a, ord=None if p == "fro" else p, axis=tuple(axis),
                               keepdims=keepdim)
    return _apply(f, x, op_name="matrix_norm")


def dist(x, y, p=2, name=None):
    def f(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype))
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)
    return _apply(f, x, y, op_name="dist")


def cond(x, p=None, name=None):
    return _apply(lambda a: jnp.linalg.cond(a, p=p), x, op_name="cond")


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return _apply(f, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)
    return _apply(f, x, y, op_name="cholesky_solve")


def qr(x, mode="reduced", name=None):
    out = _apply(lambda a: jnp.linalg.qr(a, mode=mode), x, op_name="qr")
    return out if mode != "r" else out


def svd(x, full_matrices=False, name=None):
    return _apply(lambda a: jnp.linalg.svd(a, full_matrices=full_matrices),
                  x, op_name="svd")


def svdvals(x):
    return _apply(lambda a: jnp.linalg.svd(a, compute_uv=False), x, op_name="svdvals")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _apply(lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian),
                  x, op_name="pinv")


def inv(x, name=None):
    return _apply(jnp.linalg.inv, x, op_name="inv")


def solve(x, y, name=None):
    return _apply(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return _apply(f, x, y, op_name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return _apply(f, x, y, op_name="lstsq")


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)  # paddle returns 1-based pivots
    out = _apply(f, x, op_name="lu")
    if get_infos:
        lu_mat, piv = out
        return lu_mat, piv, Tensor(jnp.zeros((), jnp.int32))
    return out


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack `lu` results into P, L, U (ref: paddle.linalg.lu_unpack).

    Pivots are 1-based sequential row swaps (LAPACK convention); the
    permutation matrix is built by composing them at trace time via gather.
    """
    def f(lu_mat, piv):
        m, n = lu_mat.shape[-2], lu_mat.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[..., :k, :])
        # sequential swaps -> permutation vector (host loop over k, static)
        perm = jnp.broadcast_to(jnp.arange(m), piv.shape[:-1] + (m,))
        for i in range(piv.shape[-1]):
            j = piv[..., i].astype(jnp.int32) - 1
            pi = jnp.take_along_axis(perm, jnp.full(piv.shape[:-1] + (1,), i), -1)
            pj = jnp.take_along_axis(perm, j[..., None], -1)
            perm = jnp.where(
                jnp.arange(m) == i, pj, jnp.where(
                    jnp.arange(m) == j[..., None], pi, perm))
        # L@U == A[perm], so P must scatter row perm[c] back to row c:
        # P[r, c] = 1 iff perm[c] == r
        P = (jnp.arange(m)[:, None] == perm[..., None, :]).astype(lu_mat.dtype)
        return P, L, U

    P, L, U = f(as_tensor_data(x), as_tensor_data(y))
    out = []
    out.append(Tensor(P) if unpack_pivots else None)
    if unpack_ludata:
        out += [Tensor(L), Tensor(U)]
    else:
        out += [None, None]
    return tuple(out)


def eig(x, name=None):
    a = np.asarray(as_tensor_data(x))
    w, v = np.linalg.eig(a)  # XLA lacks nonsymmetric eig on TPU; host fallback
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return _apply(lambda a: jnp.linalg.eigh(a, symmetrize_input=True), x, op_name="eigh")


def eigvals(x, name=None):
    a = np.asarray(as_tensor_data(x))
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def eigvalsh(x, UPLO="L", name=None):
    return _apply(lambda a: jnp.linalg.eigvalsh(a), x, op_name="eigvalsh")


def matrix_power(x, n, name=None):
    return _apply(lambda a: jnp.linalg.matrix_power(a, int(n)), x, op_name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    def f(a):
        return jnp.linalg.matrix_rank(a, tol=as_tensor_data(tol) if tol is not None else None)
    return _apply(f, x, op_name="matrix_rank")


def multi_dot(x, name=None):
    return _apply(lambda *arrs: jnp.linalg.multi_dot(arrs), *x, op_name="multi_dot")


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return _apply(f, x, y, op_name="cross")


def histogram(x, bins=100, min=0, max=0, name=None):
    a = np.asarray(as_tensor_data(x))
    lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
    hist, _ = np.histogram(a, bins=int(bins), range=(float(lo), float(hi)))
    return Tensor(jnp.asarray(hist, dtype=jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    def f(a, *w):
        return jnp.bincount(a.astype(jnp.int32), weights=w[0] if w else None,
                            minlength=int(minlength),
                            length=None)
    a = np.asarray(as_tensor_data(x))
    length = max(int(a.max()) + 1 if a.size else 0, int(minlength))
    def g(arr, *w):
        return jnp.bincount(arr.astype(jnp.int32), weights=w[0] if w else None, length=length)
    if weights is not None:
        return _apply(g, x, weights, op_name="bincount")
    return _apply(g, x, op_name="bincount")


def corrcoef(x, rowvar=True, name=None):
    return _apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, op_name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return _apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
                  x, op_name="cov")


def det(x, name=None):
    return _apply(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    def f(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return _apply(f, x, op_name="slogdet")


def matrix_exp(x, name=None):
    return _apply(jax.scipy.linalg.expm, x, op_name="matrix_exp")


def householder_product(x, tau, name=None):
    def f(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye
        idx = jnp.arange(m)
        for i in range(n):
            # Householder vector: v[i]=1, v[>i]=a[>i, i], v[<i]=0
            v = jnp.where(idx == i, jnp.ones((), a.dtype),
                          jnp.where(idx > i, a[..., :, i], jnp.zeros((), a.dtype)))
            h = jnp.eye(m, dtype=a.dtype) - t_[..., i, None, None] * jnp.einsum(
                "...i,...j->...ij", v, v)
            q = q @ h
        return q[..., :, :n]
    return _apply(f, x, tau, op_name="householder_product")
