"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py).

XLA arrays are immutable; ops like scatter/put_along_axis lower to
`.at[...]` functional updates (XLA scatter HLO) instead of in-place writes.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply
from ..framework.state import to_jnp_dtype
from .math import _ax


def cast(x, dtype):
    d = to_jnp_dtype(dtype)
    return _apply(lambda a: a.astype(d), x, op_name="cast")


astype = cast


def reshape(x, shape, name=None):
    shape = _static_shape(shape)
    return _apply(lambda a: jnp.reshape(a, shape), x, op_name="reshape")


def reshape_(x, shape, name=None):
    from ..dispatch import apply_inplace
    shape = _static_shape(shape)
    return apply_inplace(x, lambda a: jnp.reshape(a, shape), x, op_name="reshape")


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(as_tensor_data(s)) for s in shape)


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return _apply(lambda a: jnp.transpose(a, perm), x, op_name="transpose")


def moveaxis(x, source, destination, name=None):
    return _apply(lambda a: jnp.moveaxis(a, source, destination), x, op_name="moveaxis")


def swapaxes(x, axis1, axis2):
    return _apply(lambda a: jnp.swapaxes(a, int(axis1), int(axis2)), x, op_name="swapaxes")


def concat(x, axis=0, name=None):
    axis = int(as_tensor_data(axis))
    tensors = list(x)
    return _apply(lambda *arrs: jnp.concatenate(arrs, axis=axis), *tensors, op_name="concat")


def stack(x, axis=0, name=None):
    tensors = list(x)
    return _apply(lambda *arrs: jnp.stack(arrs, axis=int(axis)), *tensors, op_name="stack")


def unstack(x, axis=0, num=None):
    def f(a):
        n = num if num is not None else a.shape[axis]
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis))
    return list(_apply(f, x, op_name="unstack"))


def split(x, num_or_sections, axis=0, name=None):
    axis = int(as_tensor_data(axis))

    def f(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=axis))
        sections = [int(as_tensor_data(s)) for s in num_or_sections]
        total = a.shape[axis]
        known = [s for s in sections if s != -1]
        sections2 = [s if s != -1 else total - int(np.sum(known)) for s in sections]
        splits = np.cumsum(sections2)[:-1].tolist()
        return tuple(jnp.split(a, splits, axis=axis))
    return list(_apply(f, x, op_name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def tensor_split(x, num_or_indices, axis=0):
    def f(a):
        return tuple(jnp.array_split(a, num_or_indices, axis=int(axis)))
    return list(_apply(f, x, op_name="tensor_split"))


def squeeze(x, axis=None, name=None):
    def f(a):
        ax = _ax(axis)
        if ax is None:
            return jnp.squeeze(a)
        if isinstance(ax, int):
            ax = (ax,)
        ax = tuple(a_ for a_ in ax if a.shape[a_] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a
    return _apply(f, x, op_name="squeeze")


def unsqueeze(x, axis, name=None):
    ax = _ax(axis)
    return _apply(lambda a: jnp.expand_dims(a, ax), x, op_name="unsqueeze")


squeeze_ = squeeze
unsqueeze_ = unsqueeze


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        if nd == 0:
            return a.reshape(1)
        s, e = start_axis % nd, stop_axis % nd
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return a.reshape(new_shape)
    return _apply(f, x, op_name="flatten")


def tile(x, repeat_times, name=None):
    reps = tuple(int(as_tensor_data(r)) for r in repeat_times) \
        if not isinstance(repeat_times, int) else (int(repeat_times),)
    return _apply(lambda a: jnp.tile(a, reps), x, op_name="tile")


def expand(x, shape, name=None):
    shape = _static_shape(shape)

    def f(a):
        tgt = list(shape)
        src = list(a.shape)
        # -1 keeps the source dim; align from the right
        off = len(tgt) - len(src)
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = src[i - off] if i >= off else 1
        return jnp.broadcast_to(a, tuple(tgt))
    return _apply(f, x, op_name="expand")


def broadcast_to(x, shape, name=None):
    shape = _static_shape(shape)
    return _apply(lambda a: jnp.broadcast_to(a, shape), x, op_name="broadcast_to")


def expand_as(x, y, name=None):
    return _apply(lambda a, b: jnp.broadcast_to(a, b.shape), x, y, op_name="expand_as")


def broadcast_tensors(inputs, name=None):
    return list(_apply(lambda *arrs: jnp.broadcast_arrays(*arrs), *inputs,
                       op_name="broadcast_tensors"))


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def gather(x, index, axis=0, name=None):
    axis = int(as_tensor_data(axis))
    return _apply(lambda a, i: jnp.take(a, i.astype(jnp.int32).reshape(-1), axis=axis),
                  x, index, op_name="gather")


def gather_nd(x, index, name=None):
    def f(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))]
        return out
    return _apply(f, x, index, op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # paddle overwrite=False: zero out target rows then accumulate
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return _apply(f, x, index, updates, op_name="scatter")


def scatter_(x, index, updates, overwrite=True):
    from ..dispatch import apply_inplace
    def f(a, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return apply_inplace(x, f, x, index, updates, op_name="scatter")


def scatter_nd(index, updates, shape, name=None):
    shape = _static_shape(shape)
    def f(idx, u):
        idx = idx.astype(jnp.int32)
        out = jnp.zeros(shape, u.dtype)
        return out.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return _apply(f, index, updates, op_name="scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, u):
        idx = idx.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return _apply(f, x, index, updates, op_name="scatter_nd_add")


def put_along_axis(x, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape) if v.shape != i.shape else v
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v.astype(a.dtype), axis=int(axis), inplace=False)
        mode = {"add": "add", "multiply": "multiply", "mul": "multiply"}[reduce]
        # emulate via take/put loop-free: use at[] with open_indices
        idx = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(a.ndim)])
               for k, s in enumerate(i.shape)]
        idx[int(axis) % a.ndim] = i
        if mode == "add":
            return a.at[tuple(idx)].add(v.astype(a.dtype))
        return a.at[tuple(idx)].multiply(v.astype(a.dtype))
    return _apply(f, x, indices, values if isinstance(values, Tensor) else jnp.asarray(values),
                  op_name="put_along_axis")


def take_along_axis(x, indices, axis, name=None):
    return _apply(lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=int(axis)),
                  x, indices, op_name="take_along_axis")


def index_select(x, index, axis=0, name=None):
    return _apply(lambda a, i: jnp.take(a, i.astype(jnp.int32).reshape(-1), axis=int(axis)),
                  x, index, op_name="index_select")


def index_sample(x, index):
    def f(a, i):
        return jnp.take_along_axis(a, i.astype(jnp.int32), axis=1)
    return _apply(f, x, index, op_name="index_sample")


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        i = i.astype(jnp.int32).reshape(-1)
        moved = jnp.moveaxis(a, int(axis), 0)
        vm = jnp.moveaxis(v, int(axis), 0)
        out = moved.at[i].add(vm.astype(a.dtype))
        return jnp.moveaxis(out, 0, int(axis))
    return _apply(f, x, index, value, op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, v, *idx):
        idx = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer) else i
                    for i in idx)
        if accumulate:
            return a.at[idx].add(v.astype(a.dtype))
        return a.at[idx].set(v.astype(a.dtype))
    return _apply(f, x, value, *indices, op_name="index_put")


def masked_select(x, mask, name=None):
    # dynamic-shape op: eager only (same as reference's dygraph-only usage)
    a, m = as_tensor_data(x), as_tensor_data(mask)
    return Tensor(a[np.asarray(m).astype(bool)])


def masked_fill(x, mask, value, name=None):
    return _apply(lambda a, m: jnp.where(m, jnp.asarray(as_tensor_data(value), a.dtype), a),
                  x, mask, op_name="masked_fill")


def roll(x, shifts, axis=None, name=None):
    return _apply(lambda a: jnp.roll(a, shifts, axis=_ax(axis)), x, op_name="roll")


def flip(x, axis, name=None):
    return _apply(lambda a: jnp.flip(a, axis=_ax(axis)), x, op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return _apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, op_name="rot90")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return _apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y, op_name="where")


def nonzero(x, as_tuple=False):
    a = np.asarray(as_tensor_data(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v, dtype=jnp.int64)) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), dtype=jnp.int64))


def unbind(x, axis=0):
    return unstack(x, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._data)
        def f(a):
            return jnp.repeat(a, reps, axis=_ax(axis), total_repeat_length=int(reps.sum()))
        return _apply(f, x, op_name="repeat_interleave")
    return _apply(lambda a: jnp.repeat(a, int(repeats), axis=_ax(axis)),
                  x, op_name="repeat_interleave")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn import functional as F
    return F.pad(x, pad, mode=mode, value=value, data_format=data_format)


def slice(x, axes, starts, ends):
    def f(a):
        idx = [np.s_[:]] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            s = int(as_tensor_data(s)); e = int(as_tensor_data(e))
            idx[ax] = np.s_[s:e]
        return a[tuple(idx)]
    return _apply(f, x, op_name="slice")


def strided_slice(x, axes, starts, ends, strides):
    def f(a):
        idx = [np.s_[:]] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = np.s_[int(s):int(e):int(st)]
        return a[tuple(idx)]
    return _apply(f, x, op_name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    def f(a):
        shp = _static_shape(shape)
        offs = [0] * a.ndim if offsets is None else [int(as_tensor_data(o)) for o in offsets]
        idx = tuple(np.s_[o:o + (s if s != -1 else a.shape[d] - o)]
                    for d, (o, s) in enumerate(zip(offs, shp)))
        return a[idx]
    return _apply(f, x, op_name="crop")


def as_real(x):
    def f(a):
        return jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1)
    return _apply(f, x, op_name="as_real")


def as_complex(x):
    return _apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x, op_name="as_complex")


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def atleast_1d(*inputs):
    outs = [_apply(jnp.atleast_1d, x, op_name="atleast_1d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs):
    outs = [_apply(jnp.atleast_2d, x, op_name="atleast_2d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs):
    outs = [_apply(jnp.atleast_3d, x, op_name="atleast_3d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def unfold(x, axis, size, step, name=None):
    def f(a):
        n = (a.shape[axis] - size) // step + 1
        slices = [jax.lax.dynamic_slice_in_dim(a, int(s), size, axis)
                  for s in range(0, n * step, step)]
        return jnp.stack(slices, axis=axis)
    return _apply(f, x, op_name="unfold")


def fill_(x, value):
    x._data = jnp.full_like(x._data, as_tensor_data(value))
    return x


def zero_(x):
    x._data = jnp.zeros_like(x._data)
    return x


def fill_diagonal_(x, value, offset=0, wrap=False):
    a = x._data
    n = min(a.shape[-2], a.shape[-1])
    i = jnp.arange(n - abs(int(offset)))
    r = i + max(-int(offset), 0)
    c = i + max(int(offset), 0)
    x._data = a.at[..., r, c].set(value)
    return x
