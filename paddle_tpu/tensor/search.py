"""Search ops (ref: python/paddle/tensor/search.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply
from .math import argmax, argmin, argsort, sort, topk  # noqa: F401
from .manipulation import masked_select, nonzero, where, index_select, index_sample  # noqa: F401


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return _apply(f, sorted_sequence, values, op_name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic output shape: host computation (same as reference dygraph semantics)
    a = np.asarray(as_tensor_data(x))
    out = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(out, tuple):
        return Tensor(jnp.asarray(out))
    res = [Tensor(jnp.asarray(out[0]))]
    for extra in out[1:]:
        res.append(Tensor(jnp.asarray(extra.astype(np.int64))))
    return tuple(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = np.asarray(as_tensor_data(x))
    if axis is None:
        a = a.reshape(-1)
        ax = 0
    else:
        ax = axis
    if a.size == 0:
        vals = a
        inverse = np.zeros(0, np.int64)
        counts = np.zeros(0, np.int64)
    else:
        sl = [np.s_[:]] * a.ndim
        sl[ax] = np.s_[1:]
        sl_prev = [np.s_[:]] * a.ndim
        sl_prev[ax] = np.s_[:-1]
        diff = np.any(a[tuple(sl)] != a[tuple(sl_prev)],
                      axis=tuple(i for i in range(a.ndim) if i != ax)) \
            if a.ndim > 1 else a[1:] != a[:-1]
        keep = np.concatenate([[True], diff])
        vals = np.compress(keep, a, axis=ax)
        group = np.cumsum(keep) - 1
        inverse = group.astype(np.int64)
        counts = np.bincount(group).astype(np.int64)
    res = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        res.append(Tensor(jnp.asarray(inverse)))
    if return_counts:
        res.append(Tensor(jnp.asarray(counts)))
    return res[0] if len(res) == 1 else tuple(res)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return _apply(lambda a, b: jnp.isin(a, b, invert=invert), x, test_x, op_name="isin")
