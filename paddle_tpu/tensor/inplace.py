"""In-place tensor op variants (``add_``, ``clip_``, ...).

Mirrors the reference's inplace API surface (ref:
python/paddle/tensor/__init__.py export list — `add_`, `subtract_`,
`multiply_`, `clip_`, `exp_`, `sqrt_`, `scale_`, `lerp_`,
`put_along_axis_`, `index_put_`, ...). The reference mutates the dense
tensor's buffer in its C++ kernels; XLA arrays are immutable, so "in-place"
here means REBIND: compute out-of-place, then swap the result's buffer and
tape node onto the original Tensor object and return it. User-visible
semantics match (returns the same object, later reads see the new value,
autograd records the op); what differs is only that XLA's buffer reuse is
decided by the compiler (donation), not by the op.

The tape must reference the *pre-mutation* value, so the input is
snapshotted before the op runs (same rule as dispatch.apply_inplace).
"""
from __future__ import annotations

import functools

from ..tensor_impl import Tensor
from . import manipulation, math, random as _random


def _rebind(target: Tensor, out: Tensor):
    target._data = out._data
    target._node = out._node
    target._out_idx = out._out_idx
    if out._node is not None:
        target.stop_gradient = False
    return target


def _snapshot(x: Tensor) -> Tensor:
    snap = Tensor(x._data, stop_gradient=x.stop_gradient)
    snap._node = x._node
    snap._out_idx = x._out_idx
    return snap


def inplace_variant(fn, name=None):
    """Build the ``op_`` free function from an out-of-place ``op``."""

    @functools.wraps(fn)
    def op_(x, *args, **kwargs):
        if not isinstance(x, Tensor):
            x = Tensor(x)
        snap = _snapshot(x)
        # EVERY input aliasing x must become the snapshot, or the rebound
        # node would be its own parent (same rule as dispatch.apply_inplace)
        args = tuple(snap if a is x else a for a in args)
        kwargs = {k: (snap if v is x else v) for k, v in kwargs.items()}
        out = fn(snap, *args, **kwargs)
        return _rebind(x, out)

    op_.__name__ = name or fn.__name__ + "_"
    op_.__qualname__ = op_.__name__
    op_.__doc__ = (f"In-place variant of `{fn.__name__}` (rebinds the "
                   f"result onto the input Tensor and returns it).")
    return op_


add_ = inplace_variant(math.add)
subtract_ = inplace_variant(math.subtract)
multiply_ = inplace_variant(math.multiply)
divide_ = inplace_variant(math.divide)
remainder_ = inplace_variant(math.remainder, name="remainder_")
clip_ = inplace_variant(math.clip)
scale_ = inplace_variant(math.scale)
exp_ = inplace_variant(math.exp)
sqrt_ = inplace_variant(math.sqrt)
rsqrt_ = inplace_variant(math.rsqrt)
reciprocal_ = inplace_variant(math.reciprocal)
floor_ = inplace_variant(math.floor)
ceil_ = inplace_variant(math.ceil)
round_ = inplace_variant(math.round)
abs_ = inplace_variant(math.abs)
tanh_ = inplace_variant(math.tanh)
sigmoid_ = inplace_variant(math.sigmoid)
pow_ = inplace_variant(math.pow)
lerp_ = inplace_variant(math.lerp)
erfinv_ = inplace_variant(math.erfinv, name="erfinv_")

flatten_ = inplace_variant(manipulation.flatten)
squeeze_ = inplace_variant(manipulation.squeeze)
unsqueeze_ = inplace_variant(manipulation.unsqueeze)
put_along_axis_ = inplace_variant(manipulation.put_along_axis)
index_put_ = inplace_variant(manipulation.index_put)
index_add_ = inplace_variant(manipulation.index_add)
# reshape_/scatter_ already exist in manipulation; re-export so the module
# function and the Tensor method are the same object
reshape_ = manipulation.reshape_
scatter_ = manipulation.scatter_
# random fills are already in-place by construction
uniform_ = _random.uniform_
exponential_ = _random.exponential_

__all__ = [
    "add_", "subtract_", "multiply_", "divide_", "remainder_", "clip_",
    "scale_", "exp_", "sqrt_", "rsqrt_", "reciprocal_", "floor_", "ceil_",
    "round_", "abs_", "tanh_", "sigmoid_", "pow_", "lerp_", "erfinv_",
    "flatten_", "squeeze_", "unsqueeze_", "scatter_", "put_along_axis_",
    "index_put_", "index_add_", "reshape_", "uniform_", "exponential_",
]
