"""einsum (ref: python/paddle/tensor/einsum.py). XLA maps contractions to MXU."""
from __future__ import annotations

import jax.numpy as jnp

from ..dispatch import apply as _apply


def einsum(equation, *operands):
    if not isinstance(equation, str):
        # paddle also allows einsum(op0, op1, ..., equation=...) — not supported
        raise TypeError("einsum equation must be a string")
    return _apply(lambda *arrs: jnp.einsum(equation, *arrs), *operands, op_name="einsum")
