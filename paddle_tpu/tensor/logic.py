"""Comparison & logical ops (ref: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply


def _cmp(jfn, name):
    def op(x, y, name_=None):
        return _apply(jfn, x, y, op_name=name)
    op.__name__ = name
    return op


equal = _cmp(lambda a, b: jnp.equal(a, b), "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, name=None):
    return _apply(jnp.logical_not, x, op_name="logical_not")


def bitwise_not(x, name=None):
    return _apply(jnp.bitwise_not, x, op_name="bitwise_not")


def equal_all(x, y, name=None):
    return _apply(lambda a, b: jnp.array_equal(a, b), x, y, op_name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                  x, y, op_name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                  x, y, op_name="isclose")


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    a = as_tensor_data(x)
    return Tensor(jnp.asarray(int(np.prod(a.shape)) == 0))


def is_complex(x):
    return jnp.issubdtype(as_tensor_data(x).dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(as_tensor_data(x).dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(as_tensor_data(x).dtype, jnp.floating)
