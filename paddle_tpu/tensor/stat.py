"""Statistics ops (ref: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..dispatch import apply as _apply
from .math import _ax


def mean(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.mean(a, axis=_ax(axis), keepdims=keepdim), x, op_name="mean")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _apply(lambda a: jnp.var(a, axis=_ax(axis), ddof=1 if unbiased else 0,
                                    keepdims=keepdim), x, op_name="var")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _apply(lambda a: jnp.std(a, axis=_ax(axis), ddof=1 if unbiased else 0,
                                    keepdims=keepdim), x, op_name="std")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(a):
        if mode == "avg":
            return jnp.median(a, axis=_ax(axis), keepdims=keepdim)
        # "min" mode: lower of the two middle values
        ax = _ax(axis)
        arr = a.reshape(-1) if ax is None else a
        ax2 = 0 if ax is None else ax
        srt = jnp.sort(arr, axis=ax2)
        n = srt.shape[ax2]
        out = jnp.take(srt, (n - 1) // 2, axis=ax2)
        if keepdim:
            out = jnp.expand_dims(out, ax2) if ax is not None else out.reshape((1,) * a.ndim)
        return out
    return _apply(f, x, op_name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.nanmedian(a, axis=_ax(axis), keepdims=keepdim),
                  x, op_name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    def f(a):
        return jnp.quantile(a, jnp.asarray(q), axis=_ax(axis), keepdims=keepdim,
                            method=interpolation)
    return _apply(f, x, op_name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    def f(a):
        return jnp.nanquantile(a, jnp.asarray(q), axis=_ax(axis), keepdims=keepdim)
    return _apply(f, x, op_name="nanquantile")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = _ax(axis)
        srt = jnp.sort(a, axis=ax)
        idxs = jnp.argsort(a, axis=ax)
        val = jnp.take(srt, int(k) - 1, axis=ax)
        idx = jnp.take(idxs, int(k) - 1, axis=ax).astype(jnp.int64)
        if keepdim:
            val = jnp.expand_dims(val, ax)
            idx = jnp.expand_dims(idx, ax)
        return val, idx
    return _apply(f, x, op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = _ax(axis) % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        n = moved.shape[-1]
        # count matches for each element; pick the value with max count,
        # ties broken by the largest value (paddle returns last occurrence)
        eq = moved[..., :, None] == moved[..., None, :]
        counts = eq.sum(-1)
        best = jnp.argmax(counts + jnp.linspace(0, 0.5, n), axis=-1)
        vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
        idx = best.astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idx = jnp.expand_dims(idx, ax)
        else:
            vals = jnp.moveaxis(vals[..., None], -1, ax)[..., 0] if False else vals
        return vals, idx
    return _apply(f, x, op_name="mode")
