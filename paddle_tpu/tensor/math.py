"""Elementwise & reduction math ops (ref: python/paddle/tensor/math.py, ops.py).

Every op dispatches through `paddle_tpu.dispatch.apply`, so it is eager,
tape-recorded, and AMP-aware. On TPU these all lower to XLA HLO; elementwise
chains fuse into neighboring MXU ops automatically.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply
from ..framework.state import to_jnp_dtype


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = np.asarray(axis._data).tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _unary(jfn, name):
    def op(x, name_=None, **kw):
        return _apply(jfn, x, op_name=name)
    op.__name__ = name
    return op


def _binary(jfn, name):
    def op(x, y, name_=None):
        return _apply(jfn, x, y, op_name=name)
    op.__name__ = name
    return op


# -- elementwise unary -------------------------------------------------------
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
abs = _unary(jnp.abs, "abs")
neg = _unary(jnp.negative, "neg")
square = _unary(jnp.square, "square")
sign = _unary(jnp.sign, "sign")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda a: a - jnp.trunc(a), "frac")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
digamma = _unary(jax.scipy.special.digamma, "digamma")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
i0 = _unary(jax.scipy.special.i0, "i0")
i1 = _unary(jax.scipy.special.i1, "i1")
angle = _unary(jnp.angle, "angle")
conj = _unary(jnp.conj, "conj")
real = _unary(jnp.real, "real")
imag = _unary(jnp.imag, "imag")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")

# -- elementwise binary ------------------------------------------------------
add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")
floor_divide = _binary(jnp.floor_divide, "floor_divide")
mod = _binary(jnp.mod, "mod")
remainder = mod
floor_mod = mod
pow = _binary(lambda a, b: jnp.power(a, b), "pow")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
atan2 = _binary(jnp.arctan2, "atan2")
hypot = _binary(jnp.hypot, "hypot")
logaddexp = _binary(jnp.logaddexp, "logaddexp")
heaviside = _binary(jnp.heaviside, "heaviside")
nextafter = _binary(jnp.nextafter, "nextafter")
copysign = _binary(jnp.copysign, "copysign")
gcd = _binary(jnp.gcd, "gcd")
lcm = _binary(jnp.lcm, "lcm")
ldexp = _binary(jnp.ldexp, "ldexp")
inner = _binary(jnp.inner, "inner")
outer = _binary(lambda a, b: jnp.outer(a, b), "outer")
kron = _binary(jnp.kron, "kron")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(a, s, b):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out
    out = _apply(f, x, as_tensor_data(scale), as_tensor_data(bias), op_name="scale")
    if act == "relu":
        return _apply(jax.nn.relu, out, op_name="relu")
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _apply(lambda a: scale_b * jnp.tanh(scale_a * a), x, op_name="stanh")


def multiplex(inputs, index, name=None):
    def f(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))).astype(jnp.int32),
            axis=0)[0]
    return _apply(f, index, *inputs, op_name="multiplex")


def lerp(x, y, weight, name=None):
    return _apply(lambda a, b, w: a + w * (b - a), x, y,
                  weight if isinstance(weight, Tensor) else as_tensor_data(weight),
                  op_name="lerp")


def clip(x, min=None, max=None, name=None):
    lo = as_tensor_data(min) if min is not None else None
    hi = as_tensor_data(max) if max is not None else None
    return _apply(lambda a: jnp.clip(a, lo, hi), x, op_name="clip")


def isnan(x, name=None):
    return _apply(jnp.isnan, x, op_name="isnan")


def isinf(x, name=None):
    return _apply(jnp.isinf, x, op_name="isinf")


def isfinite(x, name=None):
    return _apply(jnp.isfinite, x, op_name="isfinite")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                  x, op_name="nan_to_num")


# -- reductions --------------------------------------------------------------
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = to_jnp_dtype(dtype)
    return _apply(lambda a: jnp.sum(a, axis=_ax(axis), keepdims=keepdim, dtype=d),
                  x, op_name="sum")


def mean(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.mean(a, axis=_ax(axis), keepdims=keepdim),
                  x, op_name="mean")


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = to_jnp_dtype(dtype)
    return _apply(lambda a: jnp.prod(a, axis=_ax(axis), keepdims=keepdim, dtype=d),
                  x, op_name="prod")


def max(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.max(a, axis=_ax(axis), keepdims=keepdim), x, op_name="max")


def min(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.min(a, axis=_ax(axis), keepdims=keepdim), x, op_name="min")


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jax.scipy.special.logsumexp(a, axis=_ax(axis), keepdims=keepdim),
                  x, op_name="logsumexp")


def cumsum(x, axis=None, dtype=None, name=None):
    d = to_jnp_dtype(dtype)
    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=_ax(axis), dtype=d)
    return _apply(f, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    d = to_jnp_dtype(dtype)
    def f(a):
        if dim is None:
            return jnp.cumprod(a.reshape(-1), dtype=d)
        return jnp.cumprod(a, axis=_ax(dim), dtype=d)
    return _apply(f, x, op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    def f(a):
        ax = _ax(axis) if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax if axis is not None else 0)
        eq = arr == vals
        idx = jnp.arange(arr.shape[ax if axis is not None else 0])
        shape = [1] * arr.ndim
        shape[ax if axis is not None else 0] = -1
        idxs = jnp.where(eq, idx.reshape(shape), 0)
        indices = jax.lax.associative_scan(jnp.maximum, idxs, axis=ax if axis is not None else 0)
        return vals, indices.astype(to_jnp_dtype(dtype))
    return _apply(f, x, op_name="cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    def f(a):
        ax = _ax(axis) if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.minimum, arr, axis=ax)
        eq = arr == vals
        idx = jnp.arange(arr.shape[ax])
        shape = [1] * arr.ndim
        shape[ax] = -1
        idxs = jnp.where(eq, idx.reshape(shape), 0)
        indices = jax.lax.associative_scan(jnp.maximum, idxs, axis=ax)
        return vals, indices.astype(to_jnp_dtype(dtype))
    return _apply(f, x, op_name="cummin")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.nansum(a, axis=_ax(axis), keepdims=keepdim,
                                       dtype=to_jnp_dtype(dtype)), x, op_name="nansum")


def nanmean(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.nanmean(a, axis=_ax(axis), keepdims=keepdim),
                  x, op_name="nanmean")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.count_nonzero(a, axis=_ax(axis), keepdims=keepdim)
                  .astype(jnp.int64), x, op_name="count_nonzero")


def all(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.all(a, axis=_ax(axis), keepdims=keepdim), x, op_name="all")


def any(x, axis=None, keepdim=False, name=None):
    return _apply(lambda a: jnp.any(a, axis=_ax(axis), keepdims=keepdim), x, op_name="any")


# -- matmul-class (MXU) ------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return _apply(f, x, y, op_name="matmul")


def mm(x, y, name=None):
    return _apply(jnp.matmul, x, y, op_name="mm")


def bmm(x, y, name=None):
    return _apply(jnp.matmul, x, y, op_name="bmm")


def mv(x, vec, name=None):
    return _apply(lambda a, v: a @ v, x, vec, op_name="mv")


def dot(x, y, name=None):
    return _apply(lambda a, b: jnp.sum(a * b, axis=-1), x, y, op_name="matmul")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _apply(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y, op_name="addmm")


def inverse(x, name=None):
    return _apply(jnp.linalg.inv, x, op_name="inverse")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                  x, op_name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
                  x, op_name="diagonal")


# -- sort/search-class (kept here for paddle.math parity surface) ------------
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmax(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else _ax(axis))
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, _ax(axis))
        elif keepdim:
            out = out.reshape((1,) * a.ndim)
        return out.astype(to_jnp_dtype(dtype))
    return _apply(f, x, op_name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmin(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else _ax(axis))
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, _ax(axis))
        elif keepdim:
            out = out.reshape((1,) * a.ndim)
        return out.astype(to_jnp_dtype(dtype))
    return _apply(f, x, op_name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=_ax(axis), descending=descending)
        return idx.astype(jnp.int64)
    return _apply(f, x, op_name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=_ax(axis))
        return jnp.flip(out, axis=_ax(axis)) if descending else out
    return _apply(f, x, op_name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(as_tensor_data(k))
    def f(a):
        ax = -1 if axis is None else _ax(axis)
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return _apply(f, x, op_name="topk")
