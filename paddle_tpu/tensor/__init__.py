"""paddle_tpu.tensor — op surface + Tensor method installation.

Mirrors python/paddle/tensor/__init__.py's monkey-patch approach
(ref: python/paddle/tensor/__init__.py `tensor_method_func`): ops are defined
as free functions, then attached as Tensor methods here.
"""
from __future__ import annotations

import builtins
import inspect

import jax.numpy as jnp
import numpy as np

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply, apply_inplace
from . import creation, random, math, manipulation, linalg, logic, search, stat
from . import extras
from . import inplace
from .einsum import einsum  # noqa: F401
from .inplace import *  # noqa: F401,F403

from .creation import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403


def rank(x):
    return Tensor(jnp.asarray(as_tensor_data(x).ndim, dtype=jnp.int32))


def shape(x):
    return Tensor(jnp.asarray(as_tensor_data(x).shape, dtype=jnp.int32))


def iinfo(dtype):
    return jnp.iinfo(dtype)


def finfo(dtype):
    return jnp.finfo(dtype)


# ---------------------------------------------------------------------------
# Tensor method installation
_BINARY_DUNDERS = {
    "__add__": math.add, "__sub__": math.subtract, "__mul__": math.multiply,
    "__truediv__": math.divide, "__floordiv__": math.floor_divide,
    "__mod__": math.mod, "__pow__": math.pow, "__matmul__": math.matmul,
    "__eq__": logic.equal, "__ne__": logic.not_equal,
    "__lt__": logic.less_than, "__le__": logic.less_equal,
    "__gt__": logic.greater_than, "__ge__": logic.greater_equal,
    "__and__": logic.logical_and, "__or__": logic.logical_or,
    "__xor__": logic.logical_xor,
}
_RBINARY_DUNDERS = {
    "__radd__": math.add, "__rmul__": math.multiply,
    "__rsub__": lambda x, y: math.subtract(y, x),
    "__rtruediv__": lambda x, y: math.divide(y, x),
    "__rfloordiv__": lambda x, y: math.floor_divide(y, x),
    "__rmod__": lambda x, y: math.mod(y, x),
    "__rpow__": lambda x, y: math.pow(y, x),
    "__rmatmul__": lambda x, y: math.matmul(y, x),
}


def _make_binop(fn, swap=False):
    def op(self, other):
        if swap:
            return fn(self, other)
        return fn(self, other)
    return op


def _getitem(self, idx):
    idx = _unwrap_index(idx)
    return _apply(lambda a: a[idx], self, op_name="getitem")


def _setitem(self, idx, value):
    idx = _unwrap_index(idx)
    if isinstance(value, Tensor):
        apply_inplace(self, lambda a, v: a.at[idx].set(v.astype(a.dtype)), self, value,
                      op_name="setitem")
    else:
        apply_inplace(self, lambda a: a.at[idx].set(jnp.asarray(value).astype(a.dtype)),
                      self, op_name="setitem")
    return self


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    if isinstance(idx, builtins.slice):
        return builtins.slice(_maybe_int(idx.start), _maybe_int(idx.stop), _maybe_int(idx.step))
    return idx


def _maybe_int(v):
    if isinstance(v, Tensor):
        return int(np.asarray(v._data))
    return v


def _iter(self):
    for i in range(self.shape[0]):
        yield self[i]


def _install_tensor_methods():
    for name, fn in _BINARY_DUNDERS.items():
        setattr(Tensor, name, _make_binop(fn))
    for name, fn in _RBINARY_DUNDERS.items():
        setattr(Tensor, name, _make_binop(fn))
    Tensor.__hash__ = object.__hash__
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    Tensor.__iter__ = _iter

    modules = [math, manipulation, linalg, logic, search, stat]
    skip = {"einsum"}
    for mod in modules:
        for name, fn in vars(mod).items():
            if name.startswith("_") or name in skip or not callable(fn):
                continue
            if inspect.ismodule(fn) or isinstance(fn, type):
                continue
            params = list(inspect.signature(fn).parameters)
            if not params or params[0] not in (
                    "x", "input", "a", "condition", "sorted_sequence"):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # einsum is not a method; selected creation helpers as methods
    Tensor.astype = manipulation.cast
    Tensor.cast = manipulation.cast
    Tensor.fill_ = manipulation.fill_
    Tensor.zero_ = manipulation.zero_
    Tensor.dim = lambda self: self.ndim
    Tensor.rank = lambda self: self.ndim
    Tensor.numel = lambda self: self.size
    Tensor.element_size = lambda self: jnp.dtype(self.dtype).itemsize

    # paddle-style in-place variants: x.add_(y) etc. rebind data on the
    # object. Single source of truth is tensor/inplace.py, whose free
    # functions already take the tensor first — install them directly.
    for _name in inplace.__all__:
        setattr(Tensor, _name, getattr(inplace, _name))


_install_tensor_methods()


# The reference binds every `tensor_method_func` name as a Tensor method
# (ref python/paddle/tensor/__init__.py). Most install above; these live
# in other modules (extras/creation/framework) and are attached once the
# top-level package finishes importing (paddle_tpu/__init__.py calls this).
_REF_METHOD_STRAYS = [
    "add_n", "broadcast_shape", "broadcast_tensors", "cdist",
    "create_parameter", "create_tensor", "cumulative_trapezoid", "diff",
    "frexp", "i0e", "i1e", "increment", "logcumsumexp", "logit",
    "multiplex", "polar", "polygamma", "reverse", "scatter_nd", "sgn",
    "shard_index", "take", "tensordot", "trapezoid", "unflatten", "vander",
    "vsplit",
]


def install_method_parity(namespace):
    for n in _REF_METHOD_STRAYS:
        fn = getattr(namespace, n, None)
        if fn is not None and not hasattr(Tensor, n):
            setattr(Tensor, n, fn)
