"""Tensor creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply
from ..framework.state import get_default_dtype, to_jnp_dtype


def _norm_dtype(dtype, default=None):
    d = to_jnp_dtype(dtype)
    return d if d is not None else default


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        arr = data._data
    else:
        arr = np.asarray(data) if not hasattr(data, "dtype") else data
        if hasattr(arr, "dtype") and arr.dtype == np.float64 and dtype is None:
            # paddle maps python/np float64 input to default dtype
            if not (isinstance(data, np.ndarray) and data.dtype == np.float64):
                arr = arr.astype(np.float32)
    arr = jnp.asarray(arr)
    d = _norm_dtype(dtype)
    if d is not None:
        arr = arr.astype(d)
    elif jnp.issubdtype(arr.dtype, jnp.floating) and not isinstance(data, (Tensor, np.ndarray)) \
            and not hasattr(data, "dtype"):
        arr = arr.astype(get_default_dtype())
    t = Tensor(arr, stop_gradient=stop_gradient)
    return t


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _norm_dtype(dtype, get_default_dtype())))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _norm_dtype(dtype, get_default_dtype())))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = as_tensor_data(fill_value)
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = jnp.bool_
        elif isinstance(fill_value, int):
            dtype = jnp.int64
        else:
            dtype = get_default_dtype()
    return Tensor(jnp.full(_shape(shape), fill_value, _norm_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(as_tensor_data(x), dtype=_norm_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(as_tensor_data(x), dtype=_norm_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(as_tensor_data(x), as_tensor_data(fill_value),
                                dtype=_norm_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = as_tensor_data(start)
    end = as_tensor_data(end) if end is not None else None
    step = as_tensor_data(step)
    if end is None:
        start, end = 0, start
    d = _norm_dtype(dtype)
    if d is None:
        py = [x for x in (start, end, step) if isinstance(x, (int, float))]
        d = jnp.int64 if all(isinstance(x, int) for x in (start, end, step)
                             if isinstance(x, (int, float))) and len(py) else get_default_dtype()
        for x in (start, end, step):
            if hasattr(x, "dtype"):
                d = x.dtype
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(as_tensor_data(start), as_tensor_data(stop), int(num),
                               dtype=_norm_dtype(dtype, get_default_dtype())))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(as_tensor_data(start), as_tensor_data(stop), int(num),
                               base=base, dtype=_norm_dtype(dtype, get_default_dtype())))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), num_columns if num_columns is None else int(num_columns),
                          dtype=_norm_dtype(dtype, get_default_dtype())))


def tril(x, diagonal=0, name=None):
    return _apply(lambda a: jnp.tril(a, k=int(diagonal)), x, op_name="tril")


def triu(x, diagonal=0, name=None):
    return _apply(lambda a: jnp.triu(a, k=int(diagonal)), x, op_name="triu")


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_norm_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_norm_dtype(dtype)))


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[as_tensor_data(t) for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(int(offset))
            base = jnp.full((n, n), padding_value, a.dtype)
            return base + jnp.diag(a, k=int(offset)) - jnp.diag(
                jnp.full((a.shape[0],), padding_value, a.dtype), k=int(offset))
        return jnp.diag(a, k=int(offset))
    return _apply(f, x, op_name="diag")


def diagflat(x, offset=0, name=None):
    return _apply(lambda a: jnp.diagflat(a, k=int(offset)), x, op_name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def f(a):
        n = a.shape[-1] + abs(int(offset))
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-int(offset), 0)
        c = idx + max(int(offset), 0)
        out = out.at[..., r, c].set(a)
        return jnp.moveaxis(jnp.moveaxis(out, -2, dim1), -1, dim2) if (dim1, dim2) != (-2, -1) else out
    return _apply(f, x, op_name="diag_embed")


def assign(x, output=None):
    data = as_tensor_data(x)
    data = jnp.asarray(data)
    if output is None:
        return Tensor(data)
    output.set_value(data)
    return output


def numel(x):
    a = as_tensor_data(x)
    return Tensor(jnp.asarray(int(np.prod(a.shape)) if a.shape else 1, dtype=jnp.int64))


def clone(x):
    return x.clone() if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(as_tensor_data(s)) if not isinstance(s, (int, np.integer)) else int(s)
                 for s in shape)


def create_tensor(dtype, name=None, persistable=False):
    """Create an (empty) Tensor of the given dtype, to be filled later with
    set_value / assignment (ref: python/paddle/tensor/creation.py
    create_tensor)."""
    return Tensor(jnp.zeros((0,), dtype=_norm_dtype(dtype)))
