"""Remaining tensor-op surface (ref: python/paddle/tensor/math.py,
manipulation.py, creation.py — the long tail of the reference's top-level
namespace). All jnp/lax compositions: jit/grad-compatible, fused by XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..dispatch import apply as _apply
from ..tensor_impl import Tensor, as_tensor_data

__all__ = [
    "logcumsumexp", "logit", "complex", "cdist", "increment", "tensordot",
    "add_n", "diff", "renorm", "sgn", "take", "frexp", "trapezoid",
    "cumulative_trapezoid", "polar", "vander", "unflatten", "i0", "i0e",
    "i1", "i1e", "polygamma", "vsplit", "reverse", "shard_index", "tolist",
    "tanh_", "ldexp", "nextafter", "heaviside", "hypot", "combinations",
]


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            a = a.astype(dtype)
        if axis is None:
            return jax.lax.cumlogsumexp(a.reshape(-1), axis=0)
        return jax.lax.cumlogsumexp(a, axis=axis)
    return _apply(f, x, op_name="logcumsumexp")


def logit(x, eps=None, name=None):
    def f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        out = jnp.log(a / (1.0 - a))
        if eps is None:
            out = jnp.where((a < 0) | (a > 1), jnp.nan, out)
        return out
    return _apply(f, x, op_name="logit")


def complex(real, imag, name=None):
    return _apply(lambda r, i: jax.lax.complex(r, i), real, imag)


def polar(abs, angle, name=None):
    return _apply(lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
                  abs, angle)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-norm distances [..., P, M] between [..., P, D], [..., M, D].
    p==2 rides the MXU via the ||x||²+||y||²-2xy expansion."""
    def f(a, b):
        if p == 2.0 and "use_mm" in compute_mode:
            a2 = jnp.sum(a * a, axis=-1, keepdims=True)        # [..., P, 1]
            b2 = jnp.sum(b * b, axis=-1)[..., None, :]         # [..., 1, M]
            ab = jnp.matmul(a, jnp.swapaxes(b, -1, -2))        # [..., P, M]
            return jnp.sqrt(jnp.maximum(a2 + b2 - 2 * ab, 0.0))
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype), axis=-1)
        if p == float("inf"):
            return jnp.max(jnp.abs(d), axis=-1)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return _apply(f, x, y, op_name="cdist")


def increment(x, value=1.0, name=None):
    """In-place scalar add (returns x, ref: tensor/math.py increment)."""
    from ..dispatch import apply_inplace
    return apply_inplace(x, lambda a: a + value, x)


def tensordot(x, y, axes=2, name=None):
    def norm_axes(ax):
        if isinstance(ax, (list, tuple)):
            a0, a1 = ax
            a0 = [a0] if isinstance(a0, int) else list(a0)
            a1 = [a1] if isinstance(a1, int) else list(a1)
            return (tuple(a0), tuple(a1))
        return int(ax)
    return _apply(lambda a, b: jnp.tensordot(a, b, axes=norm_axes(axes)),
                  x, y, op_name="matmul")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    return _apply(lambda *ts: sum(ts[1:], ts[0]), *inputs)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [t for t in (prepend, append) if t is not None]

    def f(a, *rest):
        i = 0
        pre = rest[i] if prepend is not None else None
        if prepend is not None:
            i += 1
        app = rest[i] if append is not None else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    return _apply(f, x, *args, op_name="diff")


def renorm(x, p, axis, max_norm, name=None):
    """Clip the p-norm of every slice along `axis` to max_norm."""
    def f(a):
        dims = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * factor
    return _apply(f, x, op_name="renorm")


def sgn(x, name=None):
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)
    return _apply(f, x, op_name="sgn")


def take(x, index, mode="raise", name=None):
    """Flat-index gather over the flattened tensor."""
    def f(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx2 = idx % n
        elif mode == "clip":
            idx2 = jnp.clip(idx, 0, n - 1)
        else:  # raise: negative python-style indexing, no bounds check in jit
            idx2 = jnp.where(idx < 0, idx + n, idx)
        return jnp.take(flat, idx2.astype(jnp.int32)).reshape(idx.shape)
    return _apply(f, x, index, op_name="take")


def frexp(x, name=None):
    return _apply(lambda a: jnp.frexp(a), x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    args = [x] if x is not None else []

    def f(a, *rest):
        xs = rest[0] if rest else None
        return jnp.trapezoid(a, x=xs, dx=1.0 if dx is None else dx, axis=axis)
    return _apply(f, y, *args, op_name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    args = [x] if x is not None else []

    def f(a, *rest):
        d = jnp.moveaxis(a, axis, -1)
        if rest:
            xs = jnp.moveaxis(rest[0], axis, -1) if rest[0].ndim == a.ndim \
                else rest[0]
            dxs = jnp.diff(xs, axis=-1)
        else:
            dxs = 1.0 if dx is None else dx
        avg = (d[..., 1:] + d[..., :-1]) / 2.0
        out = jnp.cumsum(avg * dxs, axis=-1)
        return jnp.moveaxis(out, -1, axis)
    return _apply(f, y, *args, op_name="trapezoid")


def vander(x, n=None, increasing=False, name=None):
    return _apply(lambda a: jnp.vander(a, N=n, increasing=increasing), x)


def unflatten(x, axis, shape, name=None):
    def f(a):
        ax = axis % a.ndim
        tgt = tuple(int(s) for s in shape)
        return a.reshape(a.shape[:ax] + tgt + a.shape[ax + 1:])
    return _apply(f, x, op_name="reshape")


def i0(x, name=None):
    return _apply(lambda a: jax.scipy.special.i0(a), x)


def i0e(x, name=None):
    return _apply(lambda a: jax.scipy.special.i0e(a), x)


def i1(x, name=None):
    return _apply(lambda a: jax.scipy.special.i1(a), x)


def i1e(x, name=None):
    return _apply(lambda a: jax.scipy.special.i1e(a), x)


def polygamma(x, n, name=None):
    return _apply(lambda a: jax.scipy.special.polygamma(int(n), a), x)


def ldexp(x, y, name=None):
    return _apply(lambda a, b: a * (2.0 ** b.astype(jnp.float32)), x, y)


def nextafter(x, y, name=None):
    return _apply(lambda a, b: jnp.nextafter(a, b), x, y)


def heaviside(x, y, name=None):
    return _apply(lambda a, b: jnp.heaviside(a, b), x, y)


def hypot(x, y, name=None):
    return _apply(lambda a, b: jnp.hypot(a, b), x, y)


def vsplit(x, num_or_indices, name=None):
    def f(a):
        assert a.ndim >= 2, "vsplit expects ndim >= 2"
        return tuple(jnp.split(a, num_or_indices, axis=0))
    return list(_apply(f, x))


def reverse(x, axis, name=None):
    ax = [axis] if isinstance(axis, int) else list(axis)
    return _apply(lambda a: jnp.flip(a, axis=tuple(ax)), x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Relabel class ids for a sharded classifier (ref: tensor/math.py
    shard_index): ids owned by this shard map to [0, shard_size), others to
    ignore_value."""
    shard_size = (index_num + nshards - 1) // nshards

    def f(a):
        lo = shard_id * shard_size
        hi = lo + shard_size
        inside = (a >= lo) & (a < hi)
        return jnp.where(inside, a - lo, ignore_value)
    return _apply(f, input, op_name="shard_index")


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    a = as_tensor_data(x)
    n = a.shape[0]
    gen = (itertools.combinations_with_replacement(range(n), r)
           if with_replacement else itertools.combinations(range(n), r))
    idx = np.array(list(gen), np.int32).reshape(-1, r)
    return _apply(lambda v: jnp.take(v, idx, axis=0), x)


def tolist(x):
    return np.asarray(jax.device_get(as_tensor_data(x))).tolist()


def tanh_(x, name=None):
    from ..dispatch import apply_inplace
    return apply_inplace(x, lambda a: jnp.tanh(a), x)
