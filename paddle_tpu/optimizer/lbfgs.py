"""L-BFGS optimizer (ref: python/paddle/incubate/optimizer/lbfgs.py,
python/paddle/optimizer/lbfgs.py).

Closure-driven quasi-Newton: history of (s, y) pairs approximates the inverse
Hessian (two-loop recursion), optional strong-Wolfe line search. The driver
loop is host-side (inherently sequential decisions); every closure evaluation
is one XLA forward+backward, so the device work stays fused.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _gather_flat(ts):
    return jnp.concatenate([jnp.ravel(t._data.astype(jnp.float32)) for t in ts])


def _gather_flat_grad(ts):
    outs = []
    for t in ts:
        g = t.grad
        outs.append(jnp.ravel(g._data.astype(jnp.float32)) if g is not None
                    else jnp.zeros(int(np.prod(t._data.shape)), jnp.float32))
    return jnp.concatenate(outs)


def _set_flat(ts, flat):
    off = 0
    for t in ts:
        n = int(np.prod(t._data.shape))
        t._data = flat[off:off + n].reshape(t._data.shape).astype(t._data.dtype)
        off += n


class LBFGS(Optimizer):
    _elementwise_update = False  # curvature history couples all elements

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s, self._y, self._rho = [], [], []
        self._prev_flat_grad = None
        self._H_diag = 1.0

    def _direction(self, flat_grad):
        q = -flat_grad
        al = []
        for s, y, rho in zip(reversed(self._s), reversed(self._y),
                             reversed(self._rho)):
            a = rho * jnp.dot(s, q)
            al.append(a)
            q = q - a * y
        q = q * self._H_diag
        for (s, y, rho), a in zip(zip(self._s, self._y, self._rho),
                                  reversed(al)):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        return q

    def _eval(self, closure, flat, d, t):
        _set_flat(self._parameter_list, flat + t * d)
        loss = closure()
        return float(np.asarray(jax.device_get(loss._data))), \
            _gather_flat_grad(self._parameter_list)

    def step(self, closure):
        """closure: callable that clears grads, computes loss, calls
        backward, returns the loss Tensor."""
        params = self._parameter_list
        assert params, "LBFGS requires parameters"
        loss = closure()
        loss_val = float(np.asarray(jax.device_get(loss._data)))
        flat_grad = _gather_flat_grad(params)
        evals = 1
        if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
            return loss

        for it in range(self.max_iter):
            d = self._direction(flat_grad)
            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self.tolerance_change:
                break
            lr = self.get_lr() if (it > 0 or self._s) else \
                min(1.0, 1.0 / max(float(jnp.sum(jnp.abs(flat_grad))), 1e-12)) \
                * self.get_lr()
            flat = _gather_flat(params)

            if self.line_search_fn == "strong_wolfe":
                t, new_loss, new_grad, ls_evals = self._strong_wolfe(
                    closure, flat, d, lr, loss_val, flat_grad, gtd)
                evals += ls_evals
            else:
                t = lr
                new_loss, new_grad = self._eval(closure, flat, d, t)
                evals += 1

            s = t * d
            y = new_grad - flat_grad
            ys = float(jnp.dot(y, s))
            if ys > 1e-10:
                if len(self._s) >= self.history_size:
                    self._s.pop(0); self._y.pop(0); self._rho.pop(0)
                self._s.append(s); self._y.append(y)
                self._rho.append(1.0 / ys)
                self._H_diag = ys / float(jnp.dot(y, y))

            delta = abs(new_loss - loss_val)
            loss_val, flat_grad = new_loss, new_grad
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
                break
            if delta < self.tolerance_change or evals >= self.max_eval:
                break
        return loss

    def _strong_wolfe(self, closure, flat, d, t, f0, g0, gtd0, c1=1e-4,
                      c2=0.9, max_ls=25):
        """Bracketing + zoom line search satisfying the strong Wolfe
        conditions (same scheme as the reference's line_search_dygraph)."""
        f_prev, g_prev, t_prev = f0, g0, 0.0
        evals = 0
        f_new, g_new = self._eval(closure, flat, d, t)
        evals += 1
        for i in range(max_ls):
            gtd_new = float(jnp.dot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or (i > 0 and f_new >= f_prev):
                return self._zoom(closure, flat, d, t_prev, t, f_prev, f_new,
                                  f0, gtd0, c1, c2, evals)
            if abs(gtd_new) <= -c2 * gtd0:
                return t, f_new, g_new, evals
            if gtd_new >= 0:
                return self._zoom(closure, flat, d, t, t_prev, f_new, f_prev,
                                  f0, gtd0, c1, c2, evals)
            t_prev, f_prev, g_prev = t, f_new, g_new
            t = t * 2.0
            f_new, g_new = self._eval(closure, flat, d, t)
            evals += 1
        return t, f_new, g_new, evals

    def _zoom(self, closure, flat, d, lo, hi, f_lo, f_hi, f0, gtd0, c1, c2,
              evals, max_zoom=25):
        g_new = None
        t = 0.5 * (lo + hi)
        for _ in range(max_zoom):
            t = 0.5 * (lo + hi)
            f_new, g_new = self._eval(closure, flat, d, t)
            evals += 1
            if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
                hi, f_hi = t, f_new
            else:
                gtd_new = float(jnp.dot(g_new, d))
                if abs(gtd_new) <= -c2 * gtd0:
                    break
                if gtd_new * (hi - lo) >= 0:
                    hi, f_hi = lo, f_lo
                lo, f_lo = t, f_new
            if abs(hi - lo) < 1e-9:
                break
        return t, f_new, g_new, evals
