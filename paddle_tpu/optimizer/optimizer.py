"""Optimizer base (ref: python/paddle/optimizer/optimizer.py).

Each optimizer defines a *pure* per-parameter rule `_update(p, g, slots, lr,
step)` used by both paths:
  * eager `.step()` — walks parameters, applies the rule on arrays;
  * functional `init_state()` / `apply_gradients()` — pytree form for the
    jit'd TrainStep, where opt slots can be sharded (ZeRO) and the whole
    update fuses into the step's XLA program (donated buffers, no host sync).

multi_precision keeps fp32 master weights for low-precision params
(ref: the reference's multi_precision master-weight machinery in
python/paddle/optimizer/optimizer.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor_impl import Tensor, Parameter
from ..framework.state import no_grad
from .lr import LRScheduler

_LOW_PRECISION = (jnp.float16, jnp.bfloat16)


class Optimizer:
    # True when _update is purely elementwise over each parameter tensor, so
    # applying it to a slice equals slicing the full-tensor update. Norm- or
    # history-based optimizers (Lamb/LARS trust ratios, LBFGS) must override
    # to False — the streamed host-offload path keys on this.
    _elementwise_update = True

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._step_count = 0
        self._accumulators = {}  # id(param) -> slots dict
        if isinstance(weight_decay, (int, float)) or weight_decay is None:
            self._coupled_wd = float(weight_decay or 0.0)
        else:  # L1/L2Decay object from regularizer module
            self._coupled_wd = weight_decay

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- eager path ----------------------------------------------------------
    def _fused_step_fn(self, config):
        """Jit-cached FUSED eager update: every parameter's rule (moment
        updates + master-weight path + param write) compiles into ONE XLA
        executable per (rule, per-param static config, signature) — a single
        dispatch per `step()` instead of one per parameter — with the old
        param and slot buffers DONATED so the in-place update stops doubling
        HBM. `config` is the static per-position (has_master, decay_on)
        tuple; shapes/dtypes are handled by jax.jit's signature cache.

        Donation follows FLAGS_donate_buffers: with it on, arrays that
        aliased the pre-step param/slot buffers (e.g. ``p.detach()`` taken
        before ``step()``, a live ``state_dict()`` snapshot, or a tape
        retained across the step — ``backward(retain_graph=True)`` then
        ``step()`` then ``backward()`` reads primals the step donated) are
        freed by the update. That matches the reference's in-place param
        write, which equally invalidates a retained graph; set the flag
        False when holding such references."""
        from .. import flags as _flags
        donate = bool(_flags._FLAGS.get("FLAGS_donate_buffers", True))
        jits = self.__dict__.setdefault("_fused_step_jits", {})
        key = (config, donate)
        fn = jits.get(key)
        if fn is None:
            import jax
            from ..framework.compilation_cache import ensure_persistent_cache
            ensure_persistent_cache()

            def upd_all(ps, gs, ss, plrs, step):
                new_ps, new_ss = [], []
                for (has_master, decay_on, wd), p, g, slots, plr in zip(
                        config, ps, gs, ss, plrs):
                    if wd:
                        # coupled (L2-into-grad) decay, fused into the same
                        # program (_apply_decay_eager semantics)
                        g = g + wd * p.astype(g.dtype)
                    if has_master:
                        slots = dict(slots)
                        master = slots.pop("master")
                        new_master, out = self._update(
                            master, g.astype(jnp.float32), slots, plr, step,
                            decay_on=decay_on)
                        out["master"] = new_master
                        new_ps.append(new_master.astype(p.dtype))
                    else:
                        new_p, out = self._update(p, g, slots, plr, step,
                                                  decay_on=decay_on)
                        new_ps.append(new_p)
                    new_ss.append(out)
                return new_ps, new_ss

            fn = jax.jit(upd_all, donate_argnums=(0, 2) if donate else ())
            jits[key] = fn
        return fn

    @no_grad()
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer constructed without parameters")
        # Any trainable tensor may be optimized (paddle allows plain Tensors
        # with stop_gradient=False in the parameter list, not just Parameter).
        params_grads = [(p, p._grad) for p in params
                        if getattr(p, "trainable", not p.stop_gradient)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        self._step_count += 1
        # Coupled decay is a per-param STATIC float, so the base rule fuses
        # into the jitted update (one dispatch total); subclasses overriding
        # _apply_decay_eager (AdamW: decoupled no-op) keep their hook.
        base_decay = type(self)._apply_decay_eager is Optimizer._apply_decay_eager
        entries = []
        for p, g in params_grads:
            if g is None:
                continue
            garr = g._data
            if base_decay:
                wd = float(self._effective_wd(p) or 0.0)
            else:
                wd = 0.0
                garr = self._apply_decay_eager(p, garr)
            slots = self._accumulators.get(id(p))
            if slots is None:
                slots = self._create_slots(p._data)
                if self._multi_precision and p._data.dtype in _LOW_PRECISION:
                    slots["master"] = p._data.astype(jnp.float32)
                self._accumulators[id(p)] = slots
            plr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            entries.append((p, garr, slots, plr, self._decay_for(p), wd))
        if not entries:
            return
        config = tuple(("master" in slots, decay_on, wd)
                       for _, _, slots, _, decay_on, wd in entries)
        fused = self._fused_step_fn(config)
        new_ps, new_ss = fused([e[0]._data for e in entries],
                               [e[1] for e in entries],
                               [e[2] for e in entries],
                               [e[3] for e in entries],
                               self._step_count)
        for (p, *_), new_p, new_s in zip(entries, new_ps, new_ss):
            p._data = new_p
            self._accumulators[id(p)] = new_s

    def _decay_for(self, p):
        """Whether weight decay applies to this param (AdamW's filter fn)."""
        return True

    def _apply_decay_eager(self, p, garr):
        """Coupled (L2-into-grad) decay; AdamW overrides for decoupled."""
        wd = self._effective_wd(p)
        if wd:
            garr = garr + wd * p._data.astype(garr.dtype)
        return garr

    def _effective_wd(self, p):
        if getattr(p, "regularizer", None) is not None:
            return float(p.regularizer._coeff)
        wd = self._coupled_wd
        if not isinstance(wd, (int, float)):
            wd = float(getattr(wd, "_coeff", 0.0))
        return wd

    def clear_grad(self, set_to_zero=True):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_gradient()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # -- functional path -----------------------------------------------------
    def init_state(self, params):
        """params: dict[name -> array]. Returns state pytree (dict of dicts)."""
        state = {"step": jnp.zeros((), jnp.int32), "slots": {}}
        for name, arr in params.items():
            slots = self._create_slots(arr)
            if self._multi_precision and arr.dtype in _LOW_PRECISION:
                slots["master"] = arr.astype(jnp.float32)
            state["slots"][name] = slots
        return state

    def apply_gradients(self, params, grads, state, lr=None, wd_mask=None):
        """Pure update. params/grads: dict[name -> array]; returns new dicts.
        wd_mask: optional dict[name -> bool] controlling weight decay."""
        lr = self.get_lr() if lr is None else lr
        step = state["step"] + 1
        new_params, new_slots = {}, {}
        for name, p in params.items():
            g = grads[name]
            if g is None:
                new_params[name] = p
                new_slots[name] = state["slots"][name]
                continue
            slots = dict(state["slots"][name])
            decay_on = wd_mask.get(name, True) if wd_mask else True
            g = self._apply_decay_functional(p, g, decay_on)
            if "master" in slots:
                master = slots.pop("master")
                new_master, slots = self._update(master, g.astype(jnp.float32),
                                                 slots, lr, step,
                                                 decay_on=decay_on)
                slots["master"] = new_master
                new_params[name] = new_master.astype(p.dtype)
            else:
                new_params[name], slots = self._update(p, g, slots, lr, step,
                                                       decay_on=decay_on)
            new_slots[name] = slots
        return new_params, {"step": step, "slots": new_slots}

    def supports_sharded_update(self):
        """True when `apply_gradients` may run on per-replica flat shards of
        params/grads/slots (weight-update sharding, distributed/
        grad_comm.py): the rule must be elementwise — slicing a flat view
        then updating must equal updating then slicing. Slot-layout checks
        (param-shaped vs packed) live in grad_comm.resolve."""
        return self._elementwise_update

    def _apply_decay_functional(self, p, g, decay_on):
        wd = self._coupled_wd
        if not isinstance(wd, (int, float)):
            wd = float(getattr(wd, "_coeff", 0.0))
        if wd and decay_on:
            g = g + wd * p.astype(g.dtype)
        return g

    # -- to be implemented by subclasses ------------------------------------
    def _create_slots(self, arr):
        return {}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        raise NotImplementedError

    # -- state dict ----------------------------------------------------------
    def state_dict(self):
        out = {"step": self._step_count}
        if self._parameter_list:
            for p in self._parameter_list:
                slots = self._accumulators.get(id(p))
                if slots:
                    for k, v in slots.items():
                        out[f"{p.name}.{k}"] = Tensor(v)
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("step", 0))
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in state:
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        if self._parameter_list:
            for p in self._parameter_list:
                slots = {}
                for key, v in state.items():
                    if key.startswith(p.name + "."):
                        slots[key[len(p.name) + 1:]] = (
                            v._data if isinstance(v, Tensor) else jnp.asarray(v))
                if slots:
                    self._accumulators[id(p)] = slots
