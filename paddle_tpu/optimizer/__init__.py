"""paddle_tpu.optimizer (ref: python/paddle/optimizer/*).

Update rules are written directly in jnp so the functional path fuses the whole
optimizer into the train step's XLA program — the TPU-native equivalent of the
reference's fused multi-tensor CUDA optimizer kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import lr  # noqa: F401
from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _update(self, p, g, slots, lr, step, decay_on=True):
        return p - lr * g.astype(p.dtype), slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _create_slots(self, arr):
        return {"velocity": jnp.zeros_like(arr, dtype=jnp.float32)}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        g32 = g.astype(jnp.float32)
        v = self._momentum * slots["velocity"] + g32
        if self._nesterov:
            upd = g32 + self._momentum * v
        else:
            upd = v
        return (p - lr * upd.astype(p.dtype)).astype(p.dtype), {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_slots(self, arr):
        return {"moment": jnp.full_like(arr, self._init_acc, dtype=jnp.float32)}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        g32 = g.astype(jnp.float32)
        m = slots["moment"] + jnp.square(g32)
        new_p = p - (lr * g32 / (jnp.sqrt(m) + self._epsilon)).astype(p.dtype)
        return new_p, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._rho = rho

    def _create_slots(self, arr):
        return {"avg_squared_grad": jnp.zeros_like(arr, dtype=jnp.float32),
                "avg_squared_update": jnp.zeros_like(arr, dtype=jnp.float32)}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        g32 = g.astype(jnp.float32)
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * jnp.square(g32)
        upd = g32 * jnp.sqrt(slots["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * jnp.square(upd)
        return (p - lr * upd.astype(p.dtype)).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_slots(self, arr):
        slots = {"mean_square": jnp.zeros_like(arr, dtype=jnp.float32),
                 "momentum": jnp.zeros_like(arr, dtype=jnp.float32)}
        if self._centered:
            slots["mean_grad"] = jnp.zeros_like(arr, dtype=jnp.float32)
        return slots

    def _update(self, p, g, slots, lr, step, decay_on=True):
        g32 = g.astype(jnp.float32)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * jnp.square(g32)
        out = {"mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum"] + lr * g32 / denom
        out["momentum"] = mom
        return (p - mom.astype(p.dtype)).astype(p.dtype), out


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, name=None,
                 moment_dtype="float32"):
        """moment_dtype: storage dtype for moment1/moment2 (update math stays
        fp32). 'bfloat16' halves optimizer-state HBM — the single-chip analog
        of the reference's ZeRO moment sharding; bf16 keeps fp32's exponent
        range so moment2 does not underflow, it only loses mantissa."""
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._moment_dtype = jnp.dtype(moment_dtype)

    def _create_slots(self, arr):
        return {"moment1": jnp.zeros_like(arr, dtype=self._moment_dtype),
                "moment2": jnp.zeros_like(arr, dtype=self._moment_dtype)}

    def _moments_fp32(self, slots):
        return (slots["moment1"].astype(jnp.float32),
                slots["moment2"].astype(jnp.float32))

    def _store_moments(self, m, v):
        d = self._moment_dtype
        return {"moment1": m.astype(d), "moment2": v.astype(d)}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        b1, b2 = self._beta1, self._beta2
        g32 = g.astype(jnp.float32)
        m0, v0 = self._moments_fp32(slots)
        m = b1 * m0 + (1 - b1) * g32
        v = b2 * v0 + (1 - b2) * jnp.square(g32)
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1 ** stepf)
        vhat = v / (1 - b2 ** stepf)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), \
            self._store_moments(m, v)


class AdamW(Adam):
    """Decoupled weight decay (ref: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, moment_dtype="float32"):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name=name,
                         moment_dtype=moment_dtype)
        self._wd = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else float(getattr(weight_decay, "_coeff", 0.0))
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _apply_decay_eager(self, p, garr):
        return garr  # decoupled: decay applied inside _update

    def _apply_decay_functional(self, p, g, decay_on):
        return g

    def _decay_for(self, p):
        if self._apply_decay_param_fun is not None:
            return bool(self._apply_decay_param_fun(p.name))
        return True

    def _update(self, p, g, slots, lr, step, decay_on=True):
        b1, b2 = self._beta1, self._beta2
        g32 = g.astype(jnp.float32)
        m0, v0 = self._moments_fp32(slots)
        m = b1 * m0 + (1 - b1) * g32
        v = b2 * v0 + (1 - b2) * jnp.square(g32)
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1 ** stepf)
        vhat = v / (1 - b2 ** stepf)
        p32 = p.astype(jnp.float32)
        if decay_on and self._wd:
            p32 = p32 * (1 - lr * self._wd)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return (p32 - upd).astype(p.dtype), self._store_moments(m, v)

    def apply_gradients(self, params, grads, state, lr=None, wd_mask=None):
        if wd_mask is None and self._apply_decay_param_fun is not None:
            wd_mask = {name: self._apply_decay_param_fun(name) for name in params}
        return super().apply_gradients(params, grads, state, lr, wd_mask)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_slots(self, arr):
        return {"moment": jnp.zeros_like(arr, dtype=jnp.float32),
                "inf_norm": jnp.zeros_like(arr, dtype=jnp.float32)}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g32))
        stepf = jnp.asarray(step, jnp.float32)
        upd = lr / (1 - self._beta1 ** stepf) * m / (u + self._epsilon)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), \
            {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    """Layer-wise adaptive moments (ref: python/paddle/optimizer/lamb.py)."""

    _elementwise_update = False  # trust ratio is a whole-tensor norm

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _create_slots(self, arr):
        return {"moment1": jnp.zeros_like(arr, dtype=jnp.float32),
                "moment2": jnp.zeros_like(arr, dtype=jnp.float32)}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        b1, b2 = self._beta1, self._beta2
        g32 = g.astype(jnp.float32)
        m = b1 * slots["moment1"] + (1 - b1) * g32
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g32)
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1 ** stepf)
        vhat = v / (1 - b2 ** stepf)
        p32 = p.astype(jnp.float32)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        if decay_on and self._wd:
            r = r + self._wd * p32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(p.dtype), {"moment1": m, "moment2": v}


__all__ = ["Optimizer", "SGD", "Momentum", "LarsMomentum", "DGCMomentum", "Adagrad", "Adadelta", "RMSProp",
           "Adam", "AdamW", "Adamax", "Lamb", "lr"]
from .lbfgs import LBFGS  # noqa: E402,F401
from .meta import (  # noqa: E402,F401
    LarsMomentum, LarsMomentumOptimizer, DGCMomentum, DGCMomentumOptimizer,
)
