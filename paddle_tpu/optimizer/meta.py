"""Fleet meta-optimizer analogs as first-class optimizers.

LARS (ref: python/paddle/distributed/fleet/meta_optimizers/
lars_optimizer.py:23 + the lars_momentum PHI kernel): layer-wise adaptive
rate scaling for large-batch SGD — per-parameter trust ratio
``||p|| / (||g|| + wd*||p|| + eps)`` scales the learning rate before a
momentum update.

DGC (ref: fleet/meta_optimizers/dgc_optimizer.py:444 DGCMomentumOptimizer +
paddle/fluid/operators/dgc_op): Deep Gradient Compression — momentum
correction with a local residual accumulator; each step only the
top-(1-sparsity) fraction of |accumulated gradient| entries fire an update,
the rest stay local. The reference sparsifies the NCCL allreduce payload;
under GSPMD the collective is compiler-emitted, so the TPU-native analog
applies the same sparsify-with-residual rule on the (already reduced)
gradient — identical convergence dynamics, expressed as a pure update rule
that fuses into the compiled train step. Dense (pre-rampup) steps run plain
momentum, matching the reference's warmup.

Both rules are pure jnp on static shapes (the DGC mask is a quantile
threshold, not a dynamic top-k gather) so they fuse into TrainStep's XLA
program like every other optimizer here.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from .optimizer import Optimizer


class LarsMomentum(Optimizer):
    """ref: LarsMomentumOptimizer (lars_optimizer.py:23 wires it under
    strategy.lars; kernel: phi lars_momentum).

    velocity = mu * velocity + local_lr * (g + wd * p)
    p        = p - velocity
    local_lr = lr * lars_coeff * ||p|| / (||g|| + wd * ||p|| + eps)
               (falls back to lr when either norm is 0)
    """

    _elementwise_update = False  # local_lr is a whole-tensor norm ratio

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 exclude_from_weight_decay=None, epsilon=0.0,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._exclude = list(exclude_from_weight_decay or [])
        self._epsilon = epsilon

    def _decay_for(self, p):
        name = getattr(p, "name", "") or ""
        return not any(term in name for term in self._exclude)

    def _create_slots(self, arr):
        return {"velocity": jnp.zeros_like(arr, dtype=jnp.float32)}

    def _update(self, p, g, slots, lr, step, decay_on=True):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        wd = self._lars_wd if decay_on else 0.0
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        trust = lr * self._lars_coeff * p_norm / (
            g_norm + wd * p_norm + self._epsilon + 1e-30)
        local_lr = jnp.where((p_norm > 0.0) & (g_norm > 0.0), trust, lr)
        v = self._momentum * slots["velocity"] + local_lr * (g32 + wd * p32)
        return (p32 - v).astype(p.dtype), {"velocity": v}


LarsMomentumOptimizer = LarsMomentum


class DGCMomentum(Optimizer):
    """ref: DGCMomentumOptimizer (dgc_optimizer.py:444).

    u = m * u + g                (momentum correction)
    v = v + u                    (local residual accumulation)
    mask = |v| >= quantile(|v|, sparsity)
    p -= lr * v * mask           (only the large entries fire)
    v, u *= (1 - mask)           (the rest stay local)

    sparsity ramps through `sparsity` list between rampup_begin_step and
    rampup_begin_step + rampup_step; before rampup begins, steps are plain
    dense momentum (the reference runs the vanilla momentum op there).
    """

    _elementwise_update = False  # sparsity mask is a whole-tensor quantile

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 parameters=None, use_nesterov=False, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None,
                 num_trainers=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(int(rampup_step), 1)
        self._sparsity = [float(s) for s in
                          (sparsity if isinstance(sparsity, (list, tuple))
                           else [sparsity])]

    def _create_slots(self, arr):
        return {"velocity": jnp.zeros_like(arr, dtype=jnp.float32),
                "residual": jnp.zeros_like(arr, dtype=jnp.float32)}

    def _sparsity_at(self, step):
        """Current sparsity (traced-step safe): index the ramp table."""
        table = jnp.asarray(self._sparsity, jnp.float32)
        per = max(math.ceil(self._rampup_step / len(self._sparsity)), 1)
        idx = jnp.clip((step - self._rampup_begin) // per, 0,
                       len(self._sparsity) - 1)
        return table[idx.astype(jnp.int32)]

    def _update(self, p, g, slots, lr, step, decay_on=True):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        u, v = slots["velocity"], slots["residual"]

        # dense branch (pre-rampup): plain momentum on the velocity slot
        u_dense = self._momentum * u + g32
        upd_dense = g32 + self._momentum * u_dense if self._nesterov \
            else u_dense

        # dgc branch: momentum correction + residual + quantile mask
        u_dgc = self._momentum * u + g32
        v_dgc = v + u_dgc
        s = self._sparsity_at(step)
        absv = jnp.abs(v_dgc)
        thr = jnp.quantile(absv.reshape(-1), jnp.clip(s, 0.0, 1.0))
        mask = (absv >= thr).astype(jnp.float32)
        fired = v_dgc * mask

        dense = step <= self._rampup_begin
        new_p = jnp.where(dense, p32 - lr * upd_dense, p32 - lr * fired)
        new_u = jnp.where(dense, u_dense, u_dgc * (1.0 - mask))
        new_v = jnp.where(dense, v, v_dgc * (1.0 - mask))
        return new_p.astype(p.dtype), {"velocity": new_u, "residual": new_v}


DGCMomentumOptimizer = DGCMomentum
