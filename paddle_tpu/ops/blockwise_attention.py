"""Blockwise (flash-style) attention in XLA.

Memory-efficient attention: lax.scan over KV blocks with online-softmax
accumulators (fp32), so the S×S score matrix is never materialized — O(S·Bk)
working set instead. Fully differentiable (scan transposes cleanly), so this is
the TRAINING path; the pallas kernel (pallas_kernels/flash_attention.py) uses
it as the reference/backward.

Layout [batch, seq, heads, head_dim] matching the reference's flash_attention
API (ref: python/paddle/incubate/nn/functional flash_attention).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=("causal", "block_k"))
def blockwise_attention(q, k, v, causal=True, block_k=512):
    """q,k,v: [B, S, H, D] -> [B, S, H, D]."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_k = min(block_k, Sk)
    nk = Sk // block_k
    assert Sk % block_k == 0, f"seq {Sk} % block {block_k} != 0"
    scale = D ** -0.5

    qf = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)  # [B,H,Sq,D]
    kf = k.astype(jnp.float32).transpose(0, 2, 1, 3)
    vf = v.astype(jnp.float32).transpose(0, 2, 1, 3)
    kblocks = kf.reshape(B, H, nk, block_k, D).transpose(2, 0, 1, 3, 4)
    vblocks = vf.reshape(B, H, nk, block_k, D).transpose(2, 0, 1, 3, 4)

    q_pos = jnp.arange(Sq)

    def body(carry, blk):
        m, l, acc = carry
        kb, vb, kidx = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)
        if causal:
            k_pos = kidx * block_k + jnp.arange(block_k)
            mask = k_pos[None, :] <= q_pos[:, None]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        return (m_new, l_new, acc_new), None

    # derive carries from qf (not fresh constants) so device-varying manual-axis
    # types propagate when running inside shard_map regions (pipeline/sp)
    l0 = jnp.zeros_like(qf[..., 0])
    m0 = l0 + _NEG_INF
    acc0 = jnp.zeros_like(qf)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0), (kblocks, vblocks, jnp.arange(nk)))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
