"""Fused GEMM+collective Pallas kernels — the ``fused`` comm backend.

PR 3's ring backend (``tp_overlap.ring_ag_gemm``/``gemm_ring_rs``) overlaps
at the SCHEDULING level: each all-gather/reduce-scatter decomposes into
mp-1 ``ppermute`` hops with chunk GEMMs issued on arrival — but every hop
still materializes its chunk in HBM before the GEMM reads it. These
kernels fuse at the KERNEL level (papers: "Optimizing Distributed ML
Communication with Fused Computation-Collective Operations"
arXiv:2305.06942; T3 arXiv:2401.16677; EQuARX arXiv:2506.17615):

* ``fused_ag_gemm`` — all-gather + GEMM: each ring step issues the async
  remote copy (RDMA + semaphore wait) of the NEXT chunk into the other
  half of a double-buffered VMEM scratch while the chunk in hand runs its
  tile GEMM; gathered activations never exist in HBM.
* ``fused_gemm_rs`` — GEMM + reduce-scatter: the per-chunk partial GEMM's
  epilogue accumulates (fp32) directly into the traveling scatter
  destination, which is RDMA'd to the next device; the full-size partial
  product ``[B, S, H]`` is never materialized.
* ``fused_ag_accum_gemm`` — the weight-gradient sibling: ring-gathers the
  activation (or cotangent) chunks while accumulating the transposed
  per-chunk GEMMs into the weight-shaped output.
* ``fused_rs_bucket`` / ``fused_ag_bucket`` — grad_comm's bucketed flat
  (n, cols) reduce-scatter / all-gather as in-kernel rings; the RS
  epilogue optionally quantizes the traveling accumulator to a bf16 wire
  (EQuARX-style: compressed on the wire, fp32 local accumulation).
* ``fused_gemm_ag`` — the SERVING engine's column-parallel projection:
  the full-contraction block GEMM's epilogue feeds the ring all-gather
  of the output directly (no HBM round trip between GEMM and
  collective). Gather-only and full-K, so the result is BITWISE equal
  to the unsharded GEMM — the sharded engine's exactness contract.

CPU tier-1 parity runs the SAME kernels in Pallas interpret mode (the
``paged_attention`` kernel set this precedent); real-TPU routing is gated
by ``supported()``. jax<0.5's interpret-mode discharge rule for remote
DMAs supports exactly ONE named mesh axis, so interpret-mode eligibility
requires a single-axis mesh (``Mesh(devs, ('mp',))``); on a real TPU the
kernels compute flat logical device ids from every bound axis and any
full-manual mesh works.

Gradients: jax cannot differentiate through DMA kernels, so
``fused_ag_gemm``/``fused_gemm_rs`` carry custom VJPs whose backward
passes are themselves fused kernels (the transpose of an AG+GEMM is a
GEMM+RS of the cotangent and vice versa — the ring reverses for free).

Every wrapper counts its trace-time dispatches (``trace_counts()``) — the
audit hook for "the fused kernel actually runs" gates; the per-step
execution ledger lives with the schedule owners (tp_overlap / grad_comm).
"""
from __future__ import annotations

import functools
import logging
import threading

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger("paddle_tpu.fused_collectives")

_VMEM = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.VMEM)
_SMEM = pl.BlockSpec(memory_space=pltpu.SMEM)

# distinct Mosaic collective ids per kernel family (barrier semaphores of
# concurrently-compiled kernels must not alias)
_CID = {"ag_gemm": 0, "gemm_rs": 1, "ag_accum": 2, "rs_bucket": 3,
        "ag_bucket": 4, "gemm_ag": 5, "gemm_ag_q": 6,
        "gemm_ppsend": 7, "gemm_pprecv": 8}


def interpret_default():
    """Interpret mode on every non-TPU backend (the tier-1 CPU path)."""
    return jax.default_backend() != "tpu"


def supported(mesh, shapes=(), why=""):
    """Routing predicate for the fused kernels (same pattern as
    ``paged_attention.paged_kernel_supported``): interpret mode needs a
    single-named-axis mesh (jax<0.5 remote-DMA discharge rule); a real TPU
    additionally wants Mosaic-friendly lane dims — pass the trailing
    (lane) dims the kernels will see in ``shapes`` where the caller knows
    them (resolve_gpt passes hidden + weight-shard widths; callers that
    only learn shapes later pass none and rely on Mosaic's own check).
    Returns (ok, reason) with the reason naming what would fix it."""
    if interpret_default():
        if len(mesh.axis_names) != 1:
            return False, (
                f"interpret-mode remote DMA (jax<0.5) supports exactly one "
                f"named mesh axis, mesh has {tuple(mesh.axis_names)} — use a "
                f"single-axis mesh (e.g. Mesh(devices, ('mp',))) for CPU "
                f"runs" + (f" [{why}]" if why else ""))
        return True, ""
    reasons = [f"dim {d} not a multiple of 128" for d in shapes
               if d % 128 != 0]
    if reasons:
        return False, ("; ".join(reasons) +
                       (f" [{why}]" if why else ""))
    return True, ""


# ---------------------------------------------------------------------------
# trace-time dispatch counters


_lock = threading.Lock()
_trace_counts = {}


def _count(name):
    with _lock:
        _trace_counts[name] = _trace_counts.get(name, 0) + 1


def trace_counts():
    """{kernel name: wrapper invocations at trace time}. Under a
    ``lax.scan`` layer stack each block position counts ONCE per trace
    (the scan body traces once), so a forward GPT trace shows exactly the
    per-block kernel positions."""
    with _lock:
        return dict(_trace_counts)


def reset_trace_counts():
    with _lock:
        _trace_counts.clear()


# ---------------------------------------------------------------------------
# ring topology helpers


def ring_ids(axis, n, mesh_axes):
    """(my ring index, right neighbor's, left neighbor's flat LOGICAL
    device id) as traced int32 scalars. ``mesh_axes`` is the static
    ((name, size), ...) tuple in mesh order; a neighbor's flat id is the
    row-major index over every bound axis with the ring axis's coordinate
    advanced by +-1 — on a single-axis mesh this degenerates to
    (idx +- 1) % n."""
    idx = lax.axis_index(axis).astype(jnp.int32)

    def flat(delta):
        if len(mesh_axes) == 1:
            return lax.rem(idx + jnp.int32(delta + n), jnp.int32(n))
        out = jnp.int32(0)
        for name, size in mesh_axes:
            coord = lax.axis_index(name).astype(jnp.int32)
            if name == axis:
                coord = lax.rem(coord + jnp.int32(delta + n), jnp.int32(n))
            out = out * jnp.int32(size) + coord
        return out

    return idx, flat(1), flat(-1)


def _rdma(src, dst, send_sem, recv_sem, right):
    return pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=send_sem, recv_sem=recv_sem,
        device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)


def _compiler_params(name, interpret):
    """Mosaic params for the real-TPU build: a collective id for the
    cross-device barrier semaphore, side effects pinned so the DMA chain
    is never DCE'd. Interpret mode takes none."""
    if interpret:
        return {}
    for cls_name in ("TPUCompilerParams", "CompilerParams"):
        cls = getattr(pltpu, cls_name, None)
        if cls is not None:
            try:
                return {"compiler_params": cls(collective_id=_CID[name],
                                               has_side_effects=True)}
            except TypeError:
                return {"compiler_params": cls(collective_id=_CID[name])}
    return {}


def _barrier(interpret):
    """Neighbor barrier before the first RDMA (real TPU only): devices may
    enter the kernel skewed; a send landing before the receiver allocated
    its scratch corrupts memory. Interpret mode executes in lockstep."""
    if interpret:
        return

    def emit(left, right):
        sem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(sem, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(sem, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(sem, 2)
    return emit


# ---------------------------------------------------------------------------
# kernel bodies (run per device inside a full-manual shard_map)


def _ag_gemm_kernel(nbr_ref, x_ref, w_ref, o_ref, comm_ref, send_sem,
                    recv_sem, cap_sem, *, n, out_dtype, interpret):
    """Ring all-gather + GEMM. comm_ref is a double-buffered VMEM chunk:
    step t GEMMs the chunk in hand (owned by src = idx - t) into its
    block-row of the output while the RDMA pushing that chunk onward is
    in flight — the transfer hides behind the MXU work, and the gathered
    operand never exists outside VMEM."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)
    comm_ref[0] = x_ref[...]

    def step(t, _):
        t = t.astype(jnp.int32)
        cur = lax.rem(t, jnp.int32(2))
        nxt = lax.rem(t + jnp.int32(1), jnp.int32(2))
        src = lax.rem(idx - t + jnp.int32(n), jnp.int32(n))
        dma = _rdma(comm_ref.at[cur], comm_ref.at[nxt], send_sem.at[cur],
                    recv_sem.at[nxt], right)

        @pl.when(t < n - 1)
        def _():
            if not interpret:
                # back-pressure: the remote slot we write must have been
                # consumed (its GEMM done) — the receiver signals capacity
                # after each step. Slots start free, so hop 0 skips it.
                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(cap_sem, 1)
            dma.start()

        o_ref[src] = lax.dot_general(
            comm_ref[cur], w_ref[...], (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(out_dtype)

        @pl.when(t < n - 1)
        def _():
            dma.wait()
            if not interpret:
                pltpu.semaphore_signal(
                    cap_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n, step, 0)


def _gemm_rs_kernel(nbr_ref, y_ref, w_ref, o_ref, acc_ref, send_ref,
                    recv_ref, send_sem, recv_sem, cap_sem, *, n, out_dtype,
                    interpret):
    """GEMM + ring reduce-scatter. The accumulator for chunk c rides the
    ring visiting every device once; each step's partial tile GEMM
    accumulates (fp32) directly into the traveling scatter destination in
    the epilogue — the full-size per-device partial product is never
    materialized. Accumulation order matches ``tp_overlap.gemm_ring_rs``
    exactly (devices c+1, c+2, ..., c), so the two backends agree
    bitwise in fp32."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)

    def step(t, _):
        t = t.astype(jnp.int32)
        c = lax.rem(idx - t - jnp.int32(1) + jnp.int32(2 * n), jnp.int32(n))
        # GEMM first: the previous hop's transfer is still in flight
        part = lax.dot_general(
            y_ref[c], w_ref[...], (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dma_prev = _rdma(send_ref, recv_ref, send_sem.at[0], recv_sem.at[0],
                         right)

        @pl.when(t > 0)
        def _():
            dma_prev.wait()
            acc_ref[...] = recv_ref[...].astype(jnp.float32) + part
            if not interpret:
                # hop t-1 consumed: recv_ref is free again — credit the
                # sender so it may overwrite it with hop t
                pltpu.semaphore_signal(
                    cap_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

        @pl.when(t == 0)
        def _():
            acc_ref[...] = part

        @pl.when(t < n - 1)
        def _():
            if not interpret:
                # hop t overwrites the receiver's single recv_ref, so it
                # must wait for the receiver's hop t-1 consumption credit
                # (hop 0's buffer starts free)
                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(cap_sem, 1)
            send_ref[...] = acc_ref[...].astype(send_ref.dtype)
            _rdma(send_ref, recv_ref, send_sem.at[0], recv_sem.at[0],
                  right).start()
        return 0

    lax.fori_loop(0, n, step, 0)
    o_ref[...] = acc_ref[...].astype(out_dtype)


def _ag_accum_kernel(nbr_ref, r_ref, st_ref, o_ref, comm_ref, acc_ref,
                     send_sem, recv_sem, cap_sem, *, n, interpret):
    """Ring all-gather + accumulated transpose-GEMM (the weight-grad
    kernel): chunks of the ring operand arrive like _ag_gemm_kernel, but
    each step contracts the chunk against the matching block of the
    stationary operand and accumulates into the weight-shaped output —
    sum_c ring_c^T @ stat_c without gathering ring into HBM."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)
    comm_ref[0] = r_ref[...]

    def step(t, _):
        t = t.astype(jnp.int32)
        cur = lax.rem(t, jnp.int32(2))
        nxt = lax.rem(t + jnp.int32(1), jnp.int32(2))
        src = lax.rem(idx - t + jnp.int32(n), jnp.int32(n))
        dma = _rdma(comm_ref.at[cur], comm_ref.at[nxt], send_sem.at[cur],
                    recv_sem.at[nxt], right)

        @pl.when(t < n - 1)
        def _():
            if not interpret:
                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(cap_sem, 1)
            dma.start()

        part = lax.dot_general(
            comm_ref[cur], st_ref[src], (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(t == 0)
        def _():
            acc_ref[...] = part

        @pl.when(t > 0)
        def _():
            acc_ref[...] += part

        @pl.when(t < n - 1)
        def _():
            dma.wait()
            if not interpret:
                pltpu.semaphore_signal(
                    cap_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n, step, 0)
    o_ref[...] = acc_ref[...]


def _rs_bucket_kernel(nbr_ref, x_ref, o_ref, acc_ref, send_ref, recv_ref,
                      send_sem, recv_sem, cap_sem, *, n, interpret):
    """grad_comm bucket ring reduce-scatter: x (n, cols) local rows, out
    (cols,) = this replica's reduced row, fp32. The traveling accumulator
    is cast to the wire dtype of send_ref/recv_ref for each hop and
    dequantized + accumulated in fp32 on receipt (EQuARX-style: the wire
    is compressed, the accumulation is not)."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)

    def step(t, _):
        t = t.astype(jnp.int32)
        c = lax.rem(idx - t - jnp.int32(1) + jnp.int32(2 * n), jnp.int32(n))
        part = x_ref[c].astype(jnp.float32)
        dma_prev = _rdma(send_ref, recv_ref, send_sem.at[0], recv_sem.at[0],
                         right)

        @pl.when(t > 0)
        def _():
            dma_prev.wait()
            acc_ref[...] = recv_ref[...].astype(jnp.float32) + part
            if not interpret:
                # hop t-1 consumed: credit the sender (see _gemm_rs_kernel)
                pltpu.semaphore_signal(
                    cap_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

        @pl.when(t == 0)
        def _():
            acc_ref[...] = part

        @pl.when(t < n - 1)
        def _():
            if not interpret:
                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(cap_sem, 1)
            send_ref[...] = acc_ref[...].astype(send_ref.dtype)
            _rdma(send_ref, recv_ref, send_sem.at[0], recv_sem.at[0],
                  right).start()
        return 0

    lax.fori_loop(0, n, step, 0)
    o_ref[...] = acc_ref[...]


def _ag_bucket_kernel(nbr_ref, x_ref, o_ref, comm_ref, send_sem, recv_sem,
                      cap_sem, *, n, interpret):
    """grad_comm bucket ring all-gather: row (cols,) -> (n, cols)."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)
    comm_ref[0] = x_ref[...]

    def step(t, _):
        t = t.astype(jnp.int32)
        cur = lax.rem(t, jnp.int32(2))
        nxt = lax.rem(t + jnp.int32(1), jnp.int32(2))
        src = lax.rem(idx - t + jnp.int32(n), jnp.int32(n))
        dma = _rdma(comm_ref.at[cur], comm_ref.at[nxt], send_sem.at[cur],
                    recv_sem.at[nxt], right)

        @pl.when(t < n - 1)
        def _():
            if not interpret:
                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(cap_sem, 1)
            dma.start()

        o_ref[src] = comm_ref[cur]

        @pl.when(t < n - 1)
        def _():
            dma.wait()
            if not interpret:
                pltpu.semaphore_signal(
                    cap_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n, step, 0)


def _gemm_ag_kernel(nbr_ref, x_ref, w_ref, o_ref, comm_ref, send_sem,
                    recv_sem, cap_sem, *, n, out_dtype, interpret):
    """GEMM + ring all-gather of the OUTPUT (the serving engine's
    column-parallel projections): each device computes its full-contraction
    column block ``x @ w_shard`` straight into the ring buffer and the
    blocks ride the ring into every device's output — the pre-collective
    block never takes an HBM round trip between the GEMM epilogue and the
    transfer. Full-contraction per block, so the gathered result is
    BITWISE identical to slicing the unsharded GEMM (the serving
    exactness contract)."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)
    # plain matmul, NOT dot_general-with-preferred-fp32: the block must be
    # bitwise equal to the column slice of the unsharded `x @ w` the
    # single-chip engine computes (a preferred_element_type dot takes a
    # different accumulation path on CPU — observed ~1e-6 drift)
    comm_ref[0] = (x_ref[...] @ w_ref[...]).astype(out_dtype)

    def step(t, _):
        t = t.astype(jnp.int32)
        cur = lax.rem(t, jnp.int32(2))
        nxt = lax.rem(t + jnp.int32(1), jnp.int32(2))
        src = lax.rem(idx - t + jnp.int32(n), jnp.int32(n))
        dma = _rdma(comm_ref.at[cur], comm_ref.at[nxt], send_sem.at[cur],
                    recv_sem.at[nxt], right)

        @pl.when(t < n - 1)
        def _():
            if not interpret:
                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(cap_sem, 1)
            dma.start()

        o_ref[src] = comm_ref[cur]

        @pl.when(t < n - 1)
        def _():
            dma.wait()
            if not interpret:
                pltpu.semaphore_signal(
                    cap_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n, step, 0)


def _gemm_ag_q_kernel(nbr_ref, x_ref, w_ref, s_ref, o_ref, comm_ref,
                      send_sem, recv_sem, cap_sem, *, n, out_dtype,
                      interpret):
    """Quantized-weight variant of ``_gemm_ag_kernel``: w is the raw
    int8/fp8 column shard and s its per-output-channel fp32 dequant
    scale — the convert + scale multiply live in the GEMM epilogue, so
    the fp weight block never exists (not in HBM, not on the wire).
    Same algebra as the jnp path ``(x @ wq.astype(dt)) * s`` — the
    quantized serving rungs' bitwise contract."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)
    comm_ref[0] = ((x_ref[...] @ w_ref[...].astype(out_dtype)) *
                   s_ref[...].astype(out_dtype)).astype(out_dtype)

    def step(t, _):
        t = t.astype(jnp.int32)
        cur = lax.rem(t, jnp.int32(2))
        nxt = lax.rem(t + jnp.int32(1), jnp.int32(2))
        src = lax.rem(idx - t + jnp.int32(n), jnp.int32(n))
        dma = _rdma(comm_ref.at[cur], comm_ref.at[nxt], send_sem.at[cur],
                    recv_sem.at[nxt], right)

        @pl.when(t < n - 1)
        def _():
            if not interpret:
                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(cap_sem, 1)
            dma.start()

        o_ref[src] = comm_ref[cur]

        @pl.when(t < n - 1)
        def _():
            dma.wait()
            if not interpret:
                pltpu.semaphore_signal(
                    cap_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n, step, 0)


# ---------------------------------------------------------------------------
# kernel-call wrappers (per-device shards, inside full-manual shard_map)


class RingMeta(tuple):
    """Hashable static config for the fused kernels: (axis, n, mesh_axes,
    interpret). mesh_axes is ((name, size), ...) in mesh order — the flat
    logical-id basis for multi-axis (real TPU) meshes."""
    __slots__ = ()

    def __new__(cls, axis, n, mesh_axes, interpret):
        return super().__new__(cls, (axis, int(n), tuple(mesh_axes),
                                     bool(interpret)))

    axis = property(lambda self: self[0])
    n = property(lambda self: self[1])
    mesh_axes = property(lambda self: self[2])
    interpret = property(lambda self: self[3])


def meta_for(mesh, axis, interpret=None):
    return RingMeta(axis, int(mesh.shape[axis]),
                    tuple((a, int(mesh.shape[a])) for a in mesh.axis_names),
                    interpret_default() if interpret is None else interpret)


def _nbr(meta):
    return jnp.stack(ring_ids(meta.axis, meta.n, meta.mesh_axes))


def _sems():
    return [pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR]


def _sems1():
    return [pltpu.SemaphoreType.DMA((1,)), pltpu.SemaphoreType.DMA((1,)),
            pltpu.SemaphoreType.REGULAR]


def _ag_gemm_call(meta, x, w):
    """[B, s, A] seq-chunk, [A, F] -> [B, n*s, F] (full sequence)."""
    _count("ag_gemm")
    n = meta.n
    B, s, A = x.shape
    F = w.shape[1]
    out = pl.pallas_call(
        functools.partial(_ag_gemm_kernel, n=n, out_dtype=x.dtype,
                          interpret=meta.interpret),
        out_shape=jax.ShapeDtypeStruct((n, B, s, F), x.dtype),
        in_specs=[_SMEM, _VMEM, _VMEM],
        scratch_shapes=[pltpu.VMEM((2, B, s, A), x.dtype)] + _sems(),
        interpret=meta.interpret,
        **_compiler_params("ag_gemm", meta.interpret),
    )(_nbr(meta), x, w)
    return out.transpose(1, 0, 2, 3).reshape(B, n * s, F)


def _gemm_rs_call(meta, y, w):
    """[B, S, F] per-device partial, [F, A] -> [B, S/n, A] reduced shard."""
    _count("gemm_rs")
    n = meta.n
    B, S, F = y.shape
    s = S // n
    A = w.shape[1]
    ys = y.reshape(B, n, s, F).transpose(1, 0, 2, 3)
    return pl.pallas_call(
        functools.partial(_gemm_rs_kernel, n=n, out_dtype=y.dtype,
                          interpret=meta.interpret),
        out_shape=jax.ShapeDtypeStruct((B, s, A), y.dtype),
        in_specs=[_SMEM, _VMEM, _VMEM],
        scratch_shapes=[pltpu.VMEM((B, s, A), jnp.float32),
                        pltpu.VMEM((B, s, A), jnp.float32),
                        pltpu.VMEM((B, s, A), jnp.float32)] + _sems1(),
        interpret=meta.interpret,
        **_compiler_params("gemm_rs", meta.interpret),
    )(_nbr(meta), ys, w)


def _ag_accum_call(meta, r, stat):
    """ring operand r [B, s, A], stationary [B, S, Bf] -> fp32 [A, Bf] =
    sum_c r_c^T @ stat_c (the weight gradient of the fused matmuls)."""
    _count("ag_accum")
    n = meta.n
    B, s, A = r.shape
    Bf = stat.shape[2]
    st = stat.reshape(B, n, s, Bf).transpose(1, 0, 2, 3)
    return pl.pallas_call(
        functools.partial(_ag_accum_kernel, n=n, interpret=meta.interpret),
        out_shape=jax.ShapeDtypeStruct((A, Bf), jnp.float32),
        in_specs=[_SMEM, _VMEM, _VMEM],
        scratch_shapes=[pltpu.VMEM((2, B, s, A), r.dtype),
                        pltpu.VMEM((A, Bf), jnp.float32)] + _sems(),
        interpret=meta.interpret,
        **_compiler_params("ag_accum", meta.interpret),
    )(_nbr(meta), r, st)


def fused_rs_bucket(meta, x, wire_dtype=None):
    """grad_comm bucket RS: (n, cols) local -> (cols,) fp32 reduced row.
    wire_dtype (None=fp32 | bf16) compresses each hop's traveling
    accumulator on the wire; accumulation stays fp32 in the epilogue."""
    _count("rs_bucket")
    n = meta.n
    cols = x.shape[1]
    wire = jnp.dtype(wire_dtype) if wire_dtype is not None else jnp.float32
    return pl.pallas_call(
        functools.partial(_rs_bucket_kernel, n=n, interpret=meta.interpret),
        out_shape=jax.ShapeDtypeStruct((cols,), jnp.float32),
        in_specs=[_SMEM, _VMEM],
        scratch_shapes=[pltpu.VMEM((cols,), jnp.float32),
                        pltpu.VMEM((cols,), wire),
                        pltpu.VMEM((cols,), wire)] + _sems1(),
        interpret=meta.interpret,
        **_compiler_params("rs_bucket", meta.interpret),
    )(_nbr(meta), x)


def fused_ag_bucket(meta, row):
    """grad_comm bucket AG: (cols,) row -> (n, cols)."""
    _count("ag_bucket")
    n = meta.n
    cols = row.shape[0]
    return pl.pallas_call(
        functools.partial(_ag_bucket_kernel, n=n, interpret=meta.interpret),
        out_shape=jax.ShapeDtypeStruct((n, cols), row.dtype),
        in_specs=[_SMEM, _VMEM],
        scratch_shapes=[pltpu.VMEM((2, cols), row.dtype)] + _sems(),
        interpret=meta.interpret,
        **_compiler_params("ag_bucket", meta.interpret),
    )(_nbr(meta), row)


def fused_gemm_ag(meta, x, w, scale=None):
    """Column-parallel GEMM + in-kernel ring all-gather of the output:
    x [..., K] replicated rows, w [K, F/n] column shard -> [..., F] with
    feature blocks in ring (= logical) order. Every block is a
    full-contraction GEMM, so the result is BITWISE identical to
    ``x @ w_full`` — the gather moves data, never changes math. The
    serving engine's out/down/lm-head projections ride this kernel under
    the ``fused`` rung.

    ``scale`` [F/n] (quantized serving): ``w`` is an int8/fp8 shard whose
    per-output-channel dequant multiply runs in the GEMM epilogue before
    the block enters the ring — the quantized mp engine's weights never
    exist at full precision anywhere."""
    n = meta.n
    lead = x.shape[:-1]
    K = x.shape[-1]
    F = w.shape[1]
    R = 1
    for s in lead:
        R *= int(s)
    if scale is None:
        _count("gemm_ag")
        out = pl.pallas_call(
            functools.partial(_gemm_ag_kernel, n=n, out_dtype=x.dtype,
                              interpret=meta.interpret),
            out_shape=jax.ShapeDtypeStruct((n, R, F), x.dtype),
            in_specs=[_SMEM, _VMEM, _VMEM],
            scratch_shapes=[pltpu.VMEM((2, R, F), x.dtype)] + _sems(),
            interpret=meta.interpret,
            **_compiler_params("gemm_ag", meta.interpret),
        )(_nbr(meta), x.reshape(R, K), w)
    else:
        _count("gemm_ag_q")
        out = pl.pallas_call(
            functools.partial(_gemm_ag_q_kernel, n=n, out_dtype=x.dtype,
                              interpret=meta.interpret),
            out_shape=jax.ShapeDtypeStruct((n, R, F), x.dtype),
            in_specs=[_SMEM, _VMEM, _VMEM, _VMEM],
            scratch_shapes=[pltpu.VMEM((2, R, F), x.dtype)] + _sems(),
            interpret=meta.interpret,
            **_compiler_params("gemm_ag_q", meta.interpret),
        )(_nbr(meta), x.reshape(R, K), w,
          scale.reshape(1, F).astype(jnp.float32))
    # [n, R, F] -> [R, n*F]: block j lands at columns j*F..(j+1)*F (chip
    # order == logical feature order for contiguous column shards)
    return out.transpose(1, 0, 2).reshape(lead + (n * F,))


# ---------------------------------------------------------------------------
# differentiable entry points (custom VJPs: the backward passes are fused
# kernels too — the transpose of AG+GEMM is GEMM+RS of the cotangent)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def fused_ag_gemm(meta, x, w):
    """x [B, s, A] seq-shard, w [A, F] shard -> [B, S, F]: the fused
    all-gather + GEMM (ColumnParallel forward)."""
    return _ag_gemm_call(meta, x, w)


def _ag_gemm_fwd(meta, x, w):
    return _ag_gemm_call(meta, x, w), (x, w)


def _ag_gemm_bwd(meta, res, g):
    x, w = res
    # dx [B, s, A]: the cotangent's GEMM+reduce-scatter with w^T
    dx = _gemm_rs_call(meta, g, w.T)
    # dw [A, F] = sum_c x_c^T g_c, accumulated while x rings past
    dw = _ag_accum_call(meta, x, g).astype(w.dtype)
    return dx, dw


fused_ag_gemm.defvjp(_ag_gemm_fwd, _ag_gemm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def fused_gemm_rs(meta, y, w):
    """y [B, S, F] per-device partial, w [F, A] shard -> [B, s, A] reduced
    seq-shard: the fused GEMM + reduce-scatter (RowParallel forward)."""
    return _gemm_rs_call(meta, y, w)


def _gemm_rs_fwd(meta, y, w):
    return _gemm_rs_call(meta, y, w), (y, w)


def _gemm_rs_bwd(meta, res, g):
    y, w = res
    # dy [B, S, F]: all-gather the seq-shard cotangent while GEMMing w^T
    dy = _ag_gemm_call(meta, g, w.T)
    # dw [F, A] = sum_c y_c^T g_c = (sum_c g_c^T y_c)^T
    dw = _ag_accum_call(meta, g, y).T.astype(w.dtype)
    return dy, dw


fused_gemm_rs.defvjp(_gemm_rs_fwd, _gemm_rs_bwd)


# ---------------------------------------------------------------------------
# pipeline-boundary kernels (FLAGS_comm_backend='pp=fused'): the LAST GEMM
# of a pipeline stage (the block's down-projection, r + (x @ w + b)) runs
# row-chunked, and each chunk's boundary RDMA to the down-ring neighbor is
# issued the moment its rows retire — the next chunk's GEMM runs under the
# transfer, so the stage-boundary activation send costs no serial time and
# never takes an HBM round trip between the epilogue and the wire.


def _pp_chunks(R):
    for c in (8, 4, 2):
        if R % c == 0 and R // c >= 1 and R >= c:
            return c
    return 1


def _gemm_ppsend_kernel(nbr_ref, x_ref, w_ref, b_ref, r_ref, y_ref,
                        recv_ref, send_sem, recv_sem, *, C, interpret):
    """y = r + (x @ w + b), the boundary rows RDMA'd to the RIGHT
    (down-ring) neighbor's recv_ref in C pipelined chunks straight from
    the GEMM epilogue — the first bytes are on the wire while later
    chunks are still being issued, and the boundary activation never
    takes an HBM round trip before the transfer. The GEMM itself runs as
    ONE full-matrix matmul: a row-chunked dot takes a shape-dependent
    accumulation path, and the fused rung must stay BITWISE equal to the
    unfused stage tail. Destination rows are disjoint per chunk, so two
    in-flight transfers (double-buffered semaphore slots) need no extra
    capacity backpressure: slot c%2 was last waited at iteration c-1."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)
    # plain matmul + the block tail's exact op order (r + (x@w + b))
    y_ref[...] = (r_ref[...] +
                  (x_ref[...] @ w_ref[...] + b_ref[0])).astype(y_ref.dtype)
    R = y_ref.shape[0]
    rc = R // C
    dmas = []
    for c in range(C):
        lo = c * rc
        hi = lo + rc
        dma = _rdma(y_ref.at[lo:hi], recv_ref.at[lo:hi],
                    send_sem.at[c % 2], recv_sem.at[c % 2], right)
        dma.start()
        dmas.append(dma)
        if c > 0:
            dmas[c - 1].wait()
    dmas[C - 1].wait()


def _gemm_pprecv_kernel(nbr_ref, gy_ref, grecv_ref, x_ref, w_ref, dx_ref,
                        dw_ref, dr_ref, gwire_ref, send_sem,
                        recv_sem, *, C, interpret):
    """Backward tick of the fused boundary: the received-value cotangent
    ``grecv`` rides UP the ring (to the left neighbor — the transpose of
    the forward hop) chunk by chunk while dx/dr rows for the previous
    chunk compute under the transfer. dw/db run ONCE over the fully
    assembled cotangent at the end — chunked accumulation would change
    the summation order and break bitwise parity with the lax reference.
    The same goes for a row-chunked dx dot (shape-dependent accumulation),
    so the per-arrival work is the elementwise cotangent assembly
    (row-independent, bitwise-safe) and all three GEMM-class reductions
    run full-matrix after the last chunk lands."""
    idx, right, left = nbr_ref[0], nbr_ref[1], nbr_ref[2]
    barrier = _barrier(interpret)
    if barrier:
        barrier(left, right)
    R = gy_ref.shape[0]
    rc = R // C

    def consume(c):
        lo = c * rc
        hi = lo + rc
        dr_ref[lo:hi] = (gy_ref[lo:hi] +
                         gwire_ref[lo:hi].astype(gy_ref.dtype))

    dmas = []
    for c in range(C):
        lo = c * rc
        hi = lo + rc
        dma = _rdma(grecv_ref.at[lo:hi], gwire_ref.at[lo:hi],
                    send_sem.at[c % 2], recv_sem.at[c % 2], left)
        dma.start()
        dmas.append(dma)
        if c > 0:
            dmas[c - 1].wait()
            consume(c - 1)
    dmas[C - 1].wait()
    consume(C - 1)
    cot = dr_ref[...]
    # exact dimension numbers autodiff emits: d(x@w)/dx = g @ w^T
    dx_ref[...] = lax.dot_general(
        cot, w_ref[...], (((1,), (1,)), ((), ()))).astype(dx_ref.dtype)
    # d(x@w)/dw = x^T g, as the contraction autodiff emits (dims 0/0).
    # The bias cotangent is NOT reduced here: an interpret-mode in-kernel
    # reduce takes a different accumulation order than the XLA-compiled
    # reduce autodiff emits — the wrapper reduces dr at the JAX level.
    dw_ref[...] = lax.dot_general(
        x_ref[...], cot, (((0,), (0,)), ((), ()))).astype(dw_ref.dtype)


def _sems_pp():
    return [pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,))]


def _gemm_ppsend_call(meta, x, w, b, r):
    _count("gemm_ppsend")
    R, K = x.shape
    F = w.shape[1]
    C = _pp_chunks(R)
    y, recv = pl.pallas_call(
        functools.partial(_gemm_ppsend_kernel, C=C,
                          interpret=meta.interpret),
        out_shape=(jax.ShapeDtypeStruct((R, F), r.dtype),
                   jax.ShapeDtypeStruct((R, F), r.dtype)),
        in_specs=[_SMEM, _VMEM, _VMEM, _VMEM, _VMEM],
        scratch_shapes=_sems_pp(),
        interpret=meta.interpret,
        **_compiler_params("gemm_ppsend", meta.interpret),
    )(_nbr(meta), x, w, b.reshape(1, F), r)
    return y, recv


def _gemm_pprecv_call(meta, gy, grecv, x, w):
    _count("gemm_pprecv")
    R, F = gy.shape
    K = x.shape[1]
    C = _pp_chunks(R)
    dx, dw, dr = pl.pallas_call(
        functools.partial(_gemm_pprecv_kernel, C=C,
                          interpret=meta.interpret),
        out_shape=(jax.ShapeDtypeStruct((R, K), x.dtype),
                   jax.ShapeDtypeStruct((K, F), w.dtype),
                   jax.ShapeDtypeStruct((R, F), gy.dtype)),
        in_specs=[_SMEM, _VMEM, _VMEM, _VMEM, _VMEM],
        scratch_shapes=[pltpu.VMEM((R, F), gy.dtype)] + _sems_pp(),
        interpret=meta.interpret,
        **_compiler_params("gemm_pprecv", meta.interpret),
    )(_nbr(meta), gy, grecv, x, w)
    return dx, dw, dr


def _pp_perms(n):
    down = [(i, (i + 1) % n) for i in range(n)]
    up = [((i + 1) % n, i) for i in range(n)]
    return down, up


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def fused_gemm_ppsend(meta, rdma, rows, x, w, b, r):
    """Fused stage tail + boundary send: ``y = r + (x @ w + b)``;
    ``recv`` = the DOWN-ring ppermute of y (what this device receives
    from its up-neighbor). ``rdma=True`` issues the hop from the GEMM
    epilogue (real remote DMA on TPU; the jax<0.5 interpret discharge
    rule supports it on a single-axis mesh); ``rdma=False`` keeps the
    same math with the hop as an explicit lax.ppermute outside the
    kernel region (multi-axis CPU meshes). ``rows`` is the caller's
    static leading-axis split of the flattened row dimension (e.g.
    (B, S)) — the bias-cotangent reduce follows it so the backward is
    BITWISE equal to autodiff of the unflattened stage tail. Both paths
    match ``gemm_ppsend_reference`` bitwise."""
    if rows is None:
        rows = (x.shape[0],)
    if rdma:
        return _gemm_ppsend_call(meta, x, w, b, r)
    _count("gemm_ppsend_local")
    y = (r + (x @ w + b)).astype(r.dtype)
    down, _ = _pp_perms(meta.n)
    return y, lax.ppermute(y, meta.axis, down)


def _gemm_ppsend_fwd(meta, rdma, rows, x, w, b, r):
    return fused_gemm_ppsend(meta, rdma, rows, x, w, b, r), (x, w)


def _gemm_ppsend_bwd(meta, rdma, rows, res, g):
    x, w = res
    if rows is None:
        rows = (x.shape[0],)
    gy, grecv = g
    if rdma:
        dx, dw, dr = _gemm_pprecv_call(meta, gy, grecv, x, w)
    else:
        _, up = _pp_perms(meta.n)
        cot = gy + lax.ppermute(grecv, meta.axis, up)
        dx = lax.dot_general(cot, w, (((1,), (1,)), ((), ())))
        dw = lax.dot_general(x, cot, (((0,), (0,)), ((), ()))).astype(w.dtype)
        dr = cot
    # the bias cotangent reduces at the JAX level over the caller's
    # original (e.g. (B, S)) axis split — the exact reduce autodiff
    # emits for the broadcast-bias transpose of the unflattened tail
    F = dr.shape[-1]
    db = jnp.sum(dr.reshape(rows + (F,)),
                 axis=tuple(range(len(rows)))).astype(w.dtype)
    return dx.astype(x.dtype), dw, db.reshape(-1), dr


fused_gemm_ppsend.defvjp(_gemm_ppsend_fwd, _gemm_ppsend_bwd)


# ---------------------------------------------------------------------------
# unfused references — the SAME schedule (chunk order, fp32 accumulation)
# expressed with lax collectives that materialize every intermediate
# buffer. The interpret-mode parity tests assert the kernels match these
# BITWISE: fusion must remove the buffers, not change the math.


def ag_gemm_reference(axis, n, x, w):
    from ...distributed.tp_overlap import ring_ag_gemm
    return ring_ag_gemm(x, w, axis, n)


def gemm_rs_reference(axis, n, y, w):
    from ...distributed.tp_overlap import gemm_ring_rs
    return gemm_ring_rs(y, w, axis, n)


def ag_accum_reference(axis, n, r, stat):
    """sum_c r_c^T @ stat_c with r chunks arriving over ppermute hops, in
    the kernel's exact accumulation order (src = idx, idx-1, ...)."""
    idx = lax.axis_index(axis)
    B, s, A = r.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    chunk = r
    acc = None
    for t in range(n):
        src = (idx - t) % n
        st = lax.dynamic_slice_in_dim(
            stat.reshape(stat.shape[0], n, s, stat.shape[2]).transpose(
                1, 0, 2, 3), src, 1, axis=0)[0]
        part = lax.dot_general(chunk, st, (((0, 1), (0, 1)), ((), ())),
                               preferred_element_type=jnp.float32)
        acc = part if acc is None else acc + part
        if t < n - 1:
            chunk = lax.ppermute(chunk, axis, perm)
    return acc


def gemm_ag_reference(axis, n, x, w):
    """Local column-block GEMM + ppermute ring all-gather of the output
    along the last axis, in the kernel's exact block placement."""
    y = x @ w
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    F = y.shape[-1]
    out = jnp.zeros(y.shape[:-1] + (n * F,), y.dtype)
    chunk = y
    for t in range(n):
        src = (idx - t) % n
        out = lax.dynamic_update_slice_in_dim(out, chunk, src * F,
                                              axis=y.ndim - 1)
        if t < n - 1:
            chunk = lax.ppermute(chunk, axis, perm)
    return out


def rs_bucket_reference(axis, n, x, wire_dtype=None):
    """Ring RS of (n, cols) rows with per-hop wire quantization, in the
    kernel's exact order (part + received, fp32)."""
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    wire = jnp.dtype(wire_dtype) if wire_dtype is not None else jnp.float32
    acc = None
    for t in range(n):
        c = (idx - t - 1) % n
        part = lax.dynamic_index_in_dim(x, c, keepdims=False).astype(
            jnp.float32)
        if acc is None:
            acc = part
        else:
            acc = lax.ppermute(acc.astype(wire), axis, perm).astype(
                jnp.float32) + part
    return acc


def gemm_ppsend_reference(axis, n, x, w, b, r):
    """The stage tail + boundary hop the fused kernel replaces, as plain
    lax: the parity tests differentiate THIS with jax autodiff and assert
    the fused custom VJP matches bitwise."""
    y = (r + (x @ w + b)).astype(r.dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return y, lax.ppermute(y, axis, perm)
