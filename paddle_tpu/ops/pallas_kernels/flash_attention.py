"""Pallas TPU flash-attention forward kernel.

Replaces the reference's fused CUDA flash_attention (ref: paddle/phi/kernels/
gpu/flash_attn_kernel.cu capability) with a TPU-native kernel: the grid walks
(batch·head, q-block, k-block); per q-block online-softmax state (m, l, acc)
lives in VMEM scratch across the k-block sweep, scores are computed on the MXU
in fp32, and causal q<k blocks are skipped entirely (predicated grid steps).

TPU layout notes (Mosaic (8,128) tiling rule): every pallas output/input block
must have its last two dims divisible by (8, 128) or equal to the full array
dims.  Per-row statistics (LSE) therefore travel lane-broadcast as
[bq, 128] tiles — shaped (BH, Sq, 128) with all 128 lanes equal — exactly the
layout the reference-quality TPU kernels use; the wrapper slices lane 0 off to
hand a compact (BH, Sq) LSE to the backward, which re-broadcasts.  The LSE
output only exists when residuals are requested, so inference pays no extra
HBM traffic.

Backward: pallas kernels in flash_attention_bwd.py (LSE saved by this
forward, scores recomputed blockwise on the MXU). The differentiable blockwise
XLA path (ops/blockwise_attention.py) remains as the interpret/fallback
reference.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..blockwise_attention import blockwise_attention
from .flash_attention_bwd import LANES, flash_attention_backward

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                causal, nk, bq, bk, scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    scale32 = jnp.float32(scale)
    neg_inf = jnp.float32(_NEG_INF)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, jnp.float32(_NEG_INF))
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (ki <= qi) if causal else (ki >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0, :, :].astype(jnp.float32)      # [bq, D]
        k = k_ref[0, :, :].astype(jnp.float32)      # [bk, D]
        v = v_ref[0, :, :].astype(jnp.float32)      # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale32  # [bq, bk]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(k_pos <= q_pos, s, neg_inf)
        # m/l live lane-broadcast in (bq, 128) scratch (TPU tiling needs
        # lane dim 128); all 128 lanes hold the same value.
        m_prev = jnp.max(m_scr[:, :], axis=1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [bq, bk]
        corr = jnp.exp(m_prev - m_new)               # [bq, 1]
        l_prev = jnp.max(l_scr[:, :], axis=1, keepdims=True)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:, :] = acc_scr[:, :] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:, :] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:, :] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(jnp.max(l_scr[:, :], axis=1, keepdims=True),
                        jnp.float32(1e-30))
        o_ref[0, :, :] = (acc_scr[:, :] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            m = jnp.max(m_scr[:, :], axis=1, keepdims=True)   # [bq, 1]
            lse = m + jnp.log(jnp.maximum(
                jnp.max(l_scr[:, :], axis=1, keepdims=True), 1e-30))
            # lane-broadcast write: (bq, 128) tile, every lane equal
            lse_ref[0, :, :] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd_kernel_nolse(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, o_ref, None, m_scr, l_scr, acc_scr, **kw)


def _pallas_forward(q, k, v, causal, block_q=256, block_k=256,
                    with_residuals=False, interpret=False):
    """q,k,v: [B, S, H, D] -> [B, S, H, D]. Head dim padded to a lane (128)
    multiple — zero columns don't change scores or outputs. With
    with_residuals, also returns the bh-layout tensors + LSE the pallas
    backward consumes."""
    if q.dtype == jnp.float64:
        # kernel accumulates in fp32 regardless; f64 only appears via the
        # framework's global x64 flag, never as a deliberate attention dtype
        q, k, v = (t.astype(jnp.float32) for t in (q, k, v))
    D0 = q.shape[-1]
    if D0 % 128 != 0:
        pad = 128 - D0 % 128
        q, k, v = (jnp.pad(t, ((0, 0), (0, 0), (0, 0), (0, pad)))
                   for t in (q, k, v))
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0
    nq, nk = Sq // block_q, Sk // block_k
    scale = D0 ** -0.5

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    grid = (B * H, nq, nk)
    interpret = interpret or jax.default_backend() != "tpu"
    kw = dict(causal=causal, nk=nk, bq=block_q, bk=block_k, scale=scale)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
    ]
    scratch = [
        pltpu.VMEM((block_q, LANES), jnp.float32),
        pltpu.VMEM((block_q, LANES), jnp.float32),
        pltpu.VMEM((block_q, D), jnp.float32),
    ]
    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    # Mosaic rejects x64-typed index math; the framework enables x64 globally
    # for dtype parity, so pin 32-bit types inside the kernel trace.
    o_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    if with_residuals:
        kernel = functools.partial(_fwd_kernel, **kw)
        # lane-broadcast LSE: (8,128)-tileable; lane 0 sliced off below so
        # the saved residual is the compact (BH, Sq)
        out_shape = (jax.ShapeDtypeStruct(qb.shape, q.dtype),
                     jax.ShapeDtypeStruct((B * H, Sq, LANES), jnp.float32))
        out_specs = (o_spec, pl.BlockSpec((1, block_q, LANES),
                                          lambda b, i, j: (b, i, 0)))
    else:
        kernel = functools.partial(_fwd_kernel_nolse, **kw)
        out_shape = jax.ShapeDtypeStruct(qb.shape, q.dtype)
        out_specs = o_spec
    with jax.enable_x64(False):
        result = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
            compiler_params=params,
            interpret=interpret,
        )(qb, kb, vb)
    if with_residuals:
        out, lse = result
        lse = lse[:, :, 0]
    else:
        out, lse = result, None
    res = (qb, kb, vb, out, lse, scale) if with_residuals else None
    out = out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    out = out[..., :D0] if D0 != D else out
    return (out, res) if with_residuals else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention_bshd(q, k, v, causal=True):
    return _pallas_forward(q, k, v, causal)


def _vjp_fwd(q, k, v, causal):
    out, res = _pallas_forward(q, k, v, causal, with_residuals=True)
    # dtype carried as a zero-length proto array (residuals must be jax types)
    return out, (res, q.shape, jnp.zeros((0,), q.dtype))


def _vjp_bwd(causal, residuals, g):
    (qb, kb, vb, ob, lse, scale), (B, Sq, H, D0), dt_proto = residuals
    in_dtype = dt_proto.dtype
    Sk = kb.shape[1]
    D = qb.shape[-1]
    gb = g
    if D != D0:
        gb = jnp.pad(g, ((0, 0), (0, 0), (0, 0), (0, D - D0)))
    gb = gb.transpose(0, 2, 1, 3).reshape(B * H, Sq, D).astype(qb.dtype)
    interpret = jax.default_backend() != "tpu"
    dqb, dkb, dvb = flash_attention_backward(qb, kb, vb, ob, lse, gb,
                                             scale, causal,
                                             interpret=interpret)

    def from_bh(x, S):
        x = x.reshape(B, H, S, D).transpose(0, 2, 1, 3).astype(in_dtype)
        return x[..., :D0] if D != D0 else x

    return from_bh(dqb, Sq), from_bh(dkb, Sk), from_bh(dvb, Sk)


flash_attention_bshd.defvjp(_vjp_fwd, _vjp_bwd)


def flash_attention_interpret(q, k, v, causal=True, block_q=256, block_k=256):
    """Interpret-mode forward (+ residuals) so kernel numerics are testable
    on CPU without a TPU."""
    return _pallas_forward(q, k, v, causal, block_q=block_q, block_k=block_k,
                           with_residuals=True, interpret=True)
