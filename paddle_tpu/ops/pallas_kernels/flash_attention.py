"""Pallas TPU flash-attention forward kernel.

Replaces the reference's fused CUDA flash_attention (ref: paddle/phi/kernels/
gpu/flash_attn_kernel.cu capability) with a TPU-native kernel: the grid walks
(batch·head, q-block, k-block); per q-block online-softmax state (m, l, acc)
lives in VMEM scratch across the k-block sweep, scores are computed on the MXU
in fp32, and causal q<k blocks are skipped entirely (predicated grid steps).

Supported in-kernel (ref: python/paddle/nn/functional/flash_attention.py:125
`flash_attention`, :269 `flash_attn_unpadded`):
  - causal masking (block-skipped, not just masked)
  - segment ids (packed varlen batches / padding masks): per-token int ids for
    q and kv; tokens attend only within their segment
  - additive bias / mask `ab` broadcastable as (B|1, H|1, Sq, Sk), added after
    the softmax scale (matches the composed XLA path's `logits*scale + mask`)
  - dropout on the normalized probabilities via the TPU PRNG, seeded per
    (batch·head, q-block, k-block) so the backward regenerates identical bits

TPU layout notes (Mosaic (8,128) tiling rule): every pallas block must have
its last two dims divisible by (8, 128) or equal to the full array dims.
Per-row statistics (LSE) travel lane-broadcast as [bq, 128] tiles — shaped
(BH, Sq, 128) with all lanes equal; the wrapper slices lane 0 off for the
compact (BH, Sq) residual. Segment ids use the standard TPU layout: q ids
lane-broadcast (B, Sq, 128), kv ids sublane-broadcast (B, 8, Sk).

Backward: pallas kernels in flash_attention_bwd.py (LSE saved by this
forward, scores recomputed blockwise on the MXU). The differentiable blockwise
XLA path (ops/blockwise_attention.py) remains as the interpret/fallback
reference.
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..blockwise_attention import blockwise_attention
from .flash_attention_bwd import (LANES, SUBLANES, _NEG_INF, dropout_keep,
                                  flash_attention_backward, segment_mask)

logger = logging.getLogger("paddle_tpu.flash_attention")


def pick_block(seq_len, preferred=256):
    """Largest power-of-two block <= preferred that divides seq_len (Mosaic
    wants >=128 lanes; smaller seqs fall back to the XLA path via
    flash_supported)."""
    for b in (preferred, 256, 128):
        if b <= preferred and seq_len % b == 0:
            return b
    return None


def flash_supported(q_shape, kv_seq=None, why="", varlen=False):
    """THE routing predicate for the pallas flash path — used by every
    caller (nn.functional SDPA, models/gpt, bench) so gating can't drift.
    Logs the reason when the kernel is skipped (a silent fallback cost
    round 2 its perf evidence). varlen packs + pads internally, so only the
    backend and head_dim gates apply to it."""
    reasons = []
    if jax.default_backend() != "tpu":
        reasons.append("backend is not TPU")
    else:
        seq, d = q_shape[1], q_shape[-1]
        if d > 256:
            reasons.append(f"head_dim {d} > 256")
        if not varlen:
            if pick_block(seq) is None:
                reasons.append(f"q seq_len {seq} not a multiple of 128")
            if kv_seq is not None and pick_block(kv_seq) is None:
                reasons.append(f"kv seq_len {kv_seq} not a multiple of 128")
    if reasons:
        logger.info("flash attention fallback to XLA path%s: %s",
                    f" ({why})" if why else "", "; ".join(reasons))
        return False
    return True


def _fwd_kernel(*refs, causal, nq, nk, bq, bk, scale, dropout_p, has_bias,
                has_seg, with_lse):
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    q_ref, k_ref, v_ref = refs[:3]
    refs = refs[3:]
    ab_ref = refs.pop(0) if has_bias else None
    qseg_ref = refs.pop(0) if has_seg else None
    kseg_ref = refs.pop(0) if has_seg else None
    o_ref = refs.pop(0)
    lse_ref = refs.pop(0) if with_lse else None
    m_scr, l_scr, acc_scr = refs

    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    neg_inf = jnp.float32(_NEG_INF)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, neg_inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal block skip: run unless the whole block is above the diagonal
    run = (ki * bk < (qi + 1) * bq) if causal else (ki >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0, :, :].astype(jnp.float32)      # [bq, D]
        k = k_ref[0, :, :].astype(jnp.float32)      # [bk, D]
        v = v_ref[0, :, :].astype(jnp.float32)      # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        if has_bias:
            s = s + ab_ref[0, 0, :, :].astype(jnp.float32)
        if has_seg:
            s = jnp.where(segment_mask(qseg_ref, kseg_ref, bq, bk), s, neg_inf)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(k_pos <= q_pos, s, neg_inf)
        # m/l live lane-broadcast in (bq, 128) scratch (TPU tiling needs
        # lane dim 128); all 128 lanes hold the same value.
        m_prev = jnp.max(m_scr[:, :], axis=1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [bq, bk]
        corr = jnp.exp(m_prev - m_new)               # [bq, 1]
        l_prev = jnp.max(l_scr[:, :], axis=1, keepdims=True)
        # normalizer uses the PRE-dropout sum: out = sum(drop(P) @ V) with
        # P = softmax (dropout after normalization, like the reference)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            keep = dropout_keep(seed_ref[0], b, qi, ki, (bq, bk), dropout_p)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        acc_scr[:, :] = acc_scr[:, :] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:, :] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:, :] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        m = jnp.max(m_scr[:, :], axis=1, keepdims=True)       # [bq, 1]
        # fully-masked rows (padding segments): every s was _NEG_INF, so
        # p=exp(0)=1 polluted acc/l — zero the output and push LSE to +big
        # so the backward's exp(s - lse) underflows to exactly 0
        masked = m <= jnp.float32(0.5 * _NEG_INF)
        l = jnp.maximum(jnp.max(l_scr[:, :], axis=1, keepdims=True),
                        jnp.float32(1e-30))
        o_ref[0, :, :] = jnp.where(
            masked, 0.0, acc_scr[:, :] / l).astype(o_ref.dtype)
        if with_lse:
            lse = jnp.where(masked, -jnp.float32(_NEG_INF),
                            m + jnp.log(jnp.maximum(
                                jnp.max(l_scr[:, :], axis=1, keepdims=True),
                                1e-30)))
            # lane-broadcast write: (bq, 128) tile, every lane equal
            lse_ref[0, :, :] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _broadcast_index(dim, idx):
    """Index-map helper for bias dims that may be 1 (broadcast)."""
    return 0 if dim == 1 else idx


def _pallas_forward(q, k, v, causal, block_q=256, block_k=256,
                    with_residuals=False, interpret=False, bias=None,
                    segment_ids=None, dropout_p=0.0, dropout_seed=None,
                    scale=None):
    """q,k,v: [B, S, H, D] -> [B, S, H, D]. Head dim padded to a lane (128)
    multiple — zero columns don't change scores or outputs.

    bias: optional additive (B|1, H|1, Sq, Sk) term (mask as -inf entries).
    segment_ids: optional (q_ids, kv_ids) int32 [B, Sq] / [B, Sk]; attention
      only within equal ids (packed varlen / padding).
    dropout_p/dropout_seed: in-kernel dropout on normalized probabilities.
    With with_residuals, also returns the bh-layout tensors + LSE the pallas
    backward consumes.
    """
    if q.dtype == jnp.float64:
        # kernel accumulates in fp32 regardless; f64 only appears via the
        # framework's global x64 flag, never as a deliberate attention dtype
        q, k, v = (t.astype(jnp.float32) for t in (q, k, v))
    D0 = q.shape[-1]
    if D0 % 128 != 0:
        pad = 128 - D0 % 128
        q, k, v = (jnp.pad(t, ((0, 0), (0, 0), (0, 0), (0, pad)))
                   for t in (q, k, v))
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = pick_block(Sq, block_q) or min(block_q, Sq)
    block_k = pick_block(Sk, block_k) or min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0, (
        f"seq lens ({Sq}, {Sk}) not divisible by blocks "
        f"({block_q}, {block_k}); gate callers with flash_supported()")
    nq, nk = Sq // block_q, Sk // block_k
    if dropout_p:
        # same packed-seed envelope as the backward: dropout_keep packs the
        # q/k block indices into 10 bits each of one prng_seed word
        assert nq < 1024 and nk < 1024, (
            f"flash-attention dropout PRNG seed packs q/k block indices into "
            f"10 bits each; got num_q_blocks={nq}, num_k_blocks={nk} — raise "
            f"block_q/block_k so both stay below 1024")
    scale = D0 ** -0.5 if scale is None else scale

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    grid = (B * H, nq, nk)
    interpret = interpret or jax.default_backend() != "tpu"
    if float(dropout_p) > 0.0 and interpret:
        raise NotImplementedError(
            "in-kernel dropout uses the TPU PRNG, which interpret mode does "
            "not emulate; off-TPU dropout routes through the composed XLA "
            "path (nn.functional.scaled_dot_product_attention)")
    has_bias = bias is not None
    has_seg = segment_ids is not None
    dropout_p = float(dropout_p)
    kw = dict(causal=causal, nq=nq, nk=nk, bq=block_q, bk=block_k, scale=scale,
              dropout_p=dropout_p, has_bias=has_bias, has_seg=has_seg,
              with_lse=with_residuals)

    operands = []
    in_specs = []
    if dropout_p > 0.0:
        assert dropout_seed is not None
        operands.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    operands += [qb, kb, vb]
    in_specs += [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
    ]
    if has_bias:
        assert bias.ndim == 4 and bias.shape[-2:] == (Sq, Sk), bias.shape
        Bb, Hb = bias.shape[:2]
        operands.append(bias)
        in_specs.append(pl.BlockSpec(
            (1, 1, block_q, block_k),
            lambda b, i, j: (_broadcast_index(Bb, b // H),
                             _broadcast_index(Hb, b % H), i, j)))
    if has_seg:
        qs, ks = segment_ids
        assert qs.shape == (B, Sq) and ks.shape == (B, Sk)
        operands.append(jax.lax.broadcast_in_dim(
            qs.astype(jnp.int32), (B, Sq, LANES), (0, 1)))
        in_specs.append(pl.BlockSpec((1, block_q, LANES),
                                     lambda b, i, j: (b // H, i, 0)))
        operands.append(jax.lax.broadcast_in_dim(
            ks.astype(jnp.int32), (B, SUBLANES, Sk), (0, 2)))
        in_specs.append(pl.BlockSpec((1, SUBLANES, block_k),
                                     lambda b, i, j: (b // H, 0, j)))

    o_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    if with_residuals:
        # lane-broadcast LSE: (8,128)-tileable; lane 0 sliced off below so
        # the saved residual is the compact (BH, Sq)
        out_shape = (jax.ShapeDtypeStruct(qb.shape, q.dtype),
                     jax.ShapeDtypeStruct((B * H, Sq, LANES), jnp.float32))
        out_specs = (o_spec, pl.BlockSpec((1, block_q, LANES),
                                          lambda b, i, j: (b, i, 0)))
    else:
        out_shape = jax.ShapeDtypeStruct(qb.shape, q.dtype)
        out_specs = o_spec
    scratch = [
        pltpu.VMEM((block_q, LANES), jnp.float32),
        pltpu.VMEM((block_q, LANES), jnp.float32),
        pltpu.VMEM((block_q, D), jnp.float32),
    ]
    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    # Mosaic rejects x64-typed index math; the framework enables x64 globally
    # for dtype parity, so pin 32-bit types inside the kernel trace.
    with jax.enable_x64(False):
        result = pl.pallas_call(
            functools.partial(_fwd_kernel, **kw),
            out_shape=out_shape,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
            compiler_params=params,
            interpret=interpret,
        )(*operands)
    if with_residuals:
        out, lse = result
        lse = lse[:, :, 0]
    else:
        out, lse = result, None
    res = (qb, kb, vb, out, lse, scale) if with_residuals else None
    out = out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    out = out[..., :D0] if D0 != D else out
    return (out, res) if with_residuals else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 6, 8, 9, 10))
def flash_attention_bshd(q, k, v, causal=True, bias=None, segment_ids=None,
                         dropout_p=0.0, dropout_seed=None, scale=None,
                         block_q=256, block_k=256):
    """Differentiable flash attention, [B, S, H, D] layout.

    bias and segment_ids participate in the forward and in the recomputed
    backward scores but receive no gradients (masks are constants; the
    reference's flash_attn likewise returns no mask/bias grad).
    block_q/block_k tile the pallas grid (both clamped to S; must divide
    it) — the autotuning surface for MFU sweeps.
    """
    return _pallas_forward(q, k, v, causal, block_q=block_q, block_k=block_k,
                           bias=bias, segment_ids=segment_ids,
                           dropout_p=dropout_p, dropout_seed=dropout_seed,
                           scale=scale)


def _vjp_fwd(q, k, v, causal, bias, segment_ids, dropout_p, dropout_seed,
             scale, block_q, block_k):
    out, res = _pallas_forward(q, k, v, causal, block_q=block_q,
                               block_k=block_k, with_residuals=True,
                               bias=bias, segment_ids=segment_ids,
                               dropout_p=dropout_p, dropout_seed=dropout_seed,
                               scale=scale)
    # dtype carried as a zero-length proto array (residuals must be jax types)
    return out, (res, bias, segment_ids, dropout_seed, q.shape,
                 jnp.zeros((0,), q.dtype))


def _vjp_bwd(causal, dropout_p, _scale_arg, block_q, block_k, residuals, g):
    ((qb, kb, vb, ob, lse, scale), bias, segment_ids, dropout_seed,
     (B, Sq, H, D0), dt_proto) = residuals
    in_dtype = dt_proto.dtype
    Sk = kb.shape[1]
    D = qb.shape[-1]
    gb = g
    if D != D0:
        gb = jnp.pad(g, ((0, 0), (0, 0), (0, 0), (0, D - D0)))
    gb = gb.transpose(0, 2, 1, 3).reshape(B * H, Sq, D).astype(qb.dtype)
    interpret = jax.default_backend() != "tpu"
    dqb, dkb, dvb = flash_attention_backward(
        qb, kb, vb, ob, lse, gb, scale, causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        bias=bias, segment_ids=segment_ids, num_heads=H,
        dropout_p=dropout_p, dropout_seed=dropout_seed)

    def from_bh(x, S):
        x = x.reshape(B, H, S, D).transpose(0, 2, 1, 3).astype(in_dtype)
        return x[..., :D0] if D != D0 else x

    # bias/segment_ids/dropout_seed are constants: None = zero cotangent
    return (from_bh(dqb, Sq), from_bh(dkb, Sk), from_bh(dvb, Sk),
            None, None, None)


flash_attention_bshd.defvjp(_vjp_fwd, _vjp_bwd)


def flash_attention_varlen(q, k, v, cu_seqlens_q, cu_seqlens_k, causal=True,
                           scale=None, dropout_p=0.0, dropout_seed=None,
                           block=256):
    """Packed varlen flash attention (ref: flash_attn_unpadded,
    python/paddle/nn/functional/flash_attention.py:269).

    q, k, v: [total_tokens, H, D] packed sequences; cu_seqlens_*: [n_seqs+1]
    cumulative token offsets. Returns [total_q_tokens, H, D]. Tokens are
    padded to a block multiple internally; padding lives in its own segment
    id so it never attends anywhere.
    """
    Tq, H, D = q.shape
    Tk = k.shape[0]

    def pad_to_block(x, T):
        rem = (-T) % block
        return (jnp.pad(x, ((0, rem),) + ((0, 0),) * (x.ndim - 1)), T + rem)

    qp, Tq_p = pad_to_block(q, Tq)
    kp, Tk_p = pad_to_block(k, Tk)
    vp, _ = pad_to_block(v, Tk)
    # token t belongs to segment searchsorted(cu, t, 'right'); padding gets
    # distinct ids on q (-1) vs kv (-2) so padded rows match nothing
    tq = jnp.arange(Tq_p, dtype=jnp.int32)
    tk = jnp.arange(Tk_p, dtype=jnp.int32)
    qseg = jnp.where(tq < Tq,
                     jnp.searchsorted(cu_seqlens_q, tq, side="right")
                     .astype(jnp.int32), -1)
    kseg = jnp.where(tk < Tk,
                     jnp.searchsorted(cu_seqlens_k, tk, side="right")
                     .astype(jnp.int32), -2)
    # packed layout: causality is per-segment; token offsets within a batch
    # row are monotone inside each segment, so global positional causality
    # composes correctly with the segment mask as long as paired q/k segments
    # start at the same offset (cu_seqlens_q == cu_seqlens_k), the
    # flash_attn_unpadded contract for causal=True.
    out = flash_attention_bshd(qp[None], kp[None], vp[None], causal,
                               None, (qseg[None], kseg[None]),
                               dropout_p, dropout_seed, scale)
    return out[0, :Tq]


def flash_attention_interpret(q, k, v, causal=True, block_q=256, block_k=256,
                              **kw):
    """Interpret-mode forward (+ residuals) so kernel numerics are testable
    on CPU without a TPU."""
    return _pallas_forward(q, k, v, causal, block_q=block_q, block_k=block_k,
                           with_residuals=True, interpret=True, **kw)
