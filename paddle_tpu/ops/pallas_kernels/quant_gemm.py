"""Weight-only quantized GEMM Pallas kernel: int8/fp8 weight tiles
dequantized in the epilogue (fp32 accumulation, per-output-channel scale
multiply), so the full-precision weight never exists in HBM.

Routing mirrors ``paged_attention``'s kernel pattern: the kernel runs on
TPU behind ``FLAGS_serving_quant_kernel`` + a shape predicate
(``quant_gemm_supported``); everywhere else (and for unsupported shapes)
the SAME algebra runs as a jnp fallback —

    ``y = (x @ wq.astype(dt)) * s.astype(dt)``

— which XLA fuses the convert+scale of into the MXU matmul epilogue
anyway. Because the per-output-channel scale factors out of each column's
full contraction, this reassociation is the one arrangement that stays
bitwise identical under column sharding: the mp engine's per-chip block
``(x @ wq_shard) * s_shard`` IS the column slice of the single-chip
product, which is why the serving mp rungs keep their bitwise contract at
every quantized dtype config.

The KERNEL itself is the exception, exactly like the paged-decode kernel:
its k-tiled fp32 accumulation + fp32 scale epilogue is numerically
equivalent but NOT bitwise identical to the jnp epilogue (one rounding
instead of two under a bf16 compute dtype, tiled contraction order). It
routes on single-chip TPU engines only — disable
``FLAGS_serving_quant_kernel`` when auditing cross-mp-degree bitwise
parity at a quantized config on TPU (the jnp/fused-ring epilogues are
the bitwise-contract paths).
"""
from __future__ import annotations

import functools
import logging
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger("paddle_tpu.quant_gemm")

_lock = threading.Lock()
_trace_counts = {"quant_gemm": 0}


def trace_counts():
    with _lock:
        return dict(_trace_counts)


def reset_trace_counts():
    with _lock:
        for k in _trace_counts:
            _trace_counts[k] = 0


def quant_gemm_supported(R, K, F, why=""):
    """Routing predicate for the Pallas quant-GEMM kernel: TPU backend +
    Mosaic-friendly shapes (the jnp fallback serves everything else)."""
    reasons = []
    if jax.default_backend() != "tpu":
        reasons.append("backend is not TPU")
    if R % 8 != 0:
        reasons.append(f"rows {R} not a multiple of 8")
    if K % 128 != 0:
        reasons.append(f"contraction dim {K} not a multiple of 128")
    if F % 128 != 0:
        reasons.append(f"out dim {F} not a multiple of 128")
    if reasons:
        logger.info("quant gemm kernel fallback to jnp%s: %s",
                    f" ({why})" if why else "", "; ".join(reasons))
        return False
    return True


def _quant_gemm_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk,
                       out_dtype):
    """Grid (F/bn, K/bk), k innermost: accumulate the int8/fp8 weight
    tile's GEMM in fp32 scratch; the LAST k-step's epilogue multiplies
    the per-output-channel scale and casts out — dequant never touches
    HBM."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot(
        x_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[...] = (acc_ref[:] * s_ref[0].astype(jnp.float32)
                      ).astype(out_dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_n", "block_k", "interpret"))
def quant_gemm_kernel(x, wq, scale, block_n=128, block_k=128,
                      interpret=False):
    """x [R, K] fp, wq [K, F] int8/fp8, scale [F] fp32 -> [R, F] in
    x.dtype. fp32 accumulation; scale multiplied in the epilogue."""
    R, K = x.shape
    F = wq.shape[1]
    bn = min(block_n, F)
    bk = min(block_k, K)
    nk = K // bk

    return pl.pallas_call(
        functools.partial(_quant_gemm_kernel, nk=nk, out_dtype=x.dtype),
        grid=(F // bn, nk),
        in_specs=[
            pl.BlockSpec((R, bk), lambda f, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda f, k: (k, f)),
            pl.BlockSpec((1, bn), lambda f, k: (0, f)),
        ],
        out_specs=pl.BlockSpec((R, bn), lambda f, k: (0, f)),
        out_shape=jax.ShapeDtypeStruct((R, F), x.dtype),
        scratch_shapes=[pltpu.VMEM((R, bn), jnp.float32)],
        interpret=interpret,
    )(x, wq, scale.reshape(1, F).astype(jnp.float32))


def quant_gemm(x, wq, scale, use_kernel=False, interpret=False):
    """Weight-only quantized projection ``x [..., K] @ wq [K, F]`` with
    the per-output-channel dequant scale [F] fused into the epilogue.
    ``use_kernel`` routes the Pallas kernel when the (static) shapes
    qualify; the jnp fallback is the identical algebra."""
    with _lock:
        _trace_counts["quant_gemm"] += 1
    lead = x.shape[:-1]
    K = x.shape[-1]
    F = wq.shape[-1]
    R = 1
    for s in lead:
        R *= int(s)
    if use_kernel and (interpret or quant_gemm_supported(R, K, F)):
        out = quant_gemm_kernel(x.reshape(R, K), wq, scale,
                                interpret=interpret)
        return out.reshape(lead + (F,))
    return (x @ wq.astype(x.dtype)) * scale.astype(x.dtype)


# ---------------------------------------------------------------------------
# LoRA-class adapter deltas: the other GEMM epilogue (serving/adapters.py)
#
# A quantized base projection and a full-precision low-rank delta COMPOSE:
# the base GEMM dequantizes in its epilogue (above) and the delta joins
# right after, before bias — so the adapted projection is
#
#     y = dequant(x @ wq) * s  (+)  (x @ A[aid]) @ B[aid]
#
# with (+) the masked compose below. The delta path is deliberately jnp:
# rank-r contractions are tiny (r ~ 8-64) and XLA fuses the pair of
# batched einsums into the surrounding epilogue on TPU.


def lora_delta(h, A_l, B_l, aid):
    """Per-slot low-rank delta for one layer: h [B, T, K] against the
    layer's adapter slabs A_l [cap, K, r] / B_l [cap, r, F], routed by the
    TRACED per-slot row ids aid [B] -> delta [B, T, F].

    Each batch row contracts only against ITS OWN adapter rows (a take
    then two batched einsums), so every row's result is bitwise
    independent of the rest of the batch — the property that lets a
    mixed-adapter engine batch stay bitwise-equal to per-adapter solo
    runs, exactly like the base matmuls. The LoRA ``alpha/r`` scale was
    folded into B at load time (AdapterRegistry.load) and rank padding
    is zero columns/rows, so this is scale-free and padding-exact."""
    Aa = jnp.take(A_l, aid, axis=0).astype(h.dtype)          # [B, K, r]
    Ba = jnp.take(B_l, aid, axis=0).astype(h.dtype)          # [B, r, F]
    xa = jnp.einsum("btk,bkr->btr", h, Aa)
    return jnp.einsum("btr,brf->btf", xa, Ba)


def compose_delta(base, delta, aid):
    """Join a delta onto the base projection output, per slot: rows with
    aid == 0 (base model) keep ``base`` BITWISE — a where-select, not
    ``base + 0.0``, because IEEE ``-0.0 + 0.0`` is ``+0.0`` and the
    mixed-batch parity contract requires base-model rows to be
    byte-identical to an adapters-off engine. Element-wise, so under mp
    it commutes with the output-channel all-gather: composing the local
    column block before the gather equals composing after it."""
    return jnp.where((aid > 0)[:, None, None], base + delta, base)
