"""Pallas TPU flash-attention backward kernels.

Standard flash-attention backward (Dao et al.) mapped to the TPU memory
hierarchy: the forward saves only O and the per-row logsumexp (LSE); the
backward recomputes score blocks on the MXU in fp32 and accumulates dQ (one
kernel, k-sweep in VMEM scratch) and dK/dV (one kernel, q-sweep in VMEM
scratch). Nothing S×S ever touches HBM, and causal off-diagonal blocks are
skipped via predicated grid steps — same blocking discipline as the forward
kernel in flash_attention.py.

Per-row vectors (LSE, delta) are fed lane-broadcast as (BH, Sq, 128) tiles —
Mosaic's (8,128) tiling rule forbids a (1, block_q) block over a (BH, Sq)
array — and reduced back to [bq, 1] inside the kernel with a lane-max (all
lanes equal).

Replaces the reference's fused CUDA flash_attn_grad kernel (ref: paddle/phi/
kernels/gpu/flash_attn_grad_kernel.cu capability).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
LANES = 128


def _row_stat(ref):
    """Collapse a lane-broadcast [bq, LANES] block to [bq, 1] (lanes equal)."""
    return jnp.max(ref[0, :, :].astype(jnp.float32), axis=1, keepdims=True)


def _recompute_p(q_ref, k_ref, lse_ref, qi, ki, bq, bk, scale, causal):
    q = q_ref[0, :, :].astype(jnp.float32)              # [bq, D]
    k = k_ref[0, :, :].astype(jnp.float32)              # [bk, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    if causal:
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(k_pos <= q_pos, s, jnp.float32(_NEG_INF))
    lse = _row_stat(lse_ref)                            # [bq, 1]
    return q, k, jnp.exp(s - lse)                       # p: [bq, bk]


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, causal, nk, bq, bk, scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = (ki <= qi) if causal else (ki >= 0)

    @pl.when(run)
    def _block():
        _, k, p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, bq, bk, scale,
                               causal)
        do = do_ref[0, :, :].astype(jnp.float32)        # [bq, D]
        v = v_ref[0, :, :].astype(jnp.float32)          # [bk, D]
        delta = _row_stat(delta_ref)                    # [bq, 1]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * jnp.float32(scale)
        dq_scr[:, :] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, :, :] = dq_scr[:, :].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, causal, nq, bq, bk, scale):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # causal: q-block contributes to this k-block only when qi >= ki
    run = (qi >= ki) if causal else (qi >= 0)

    @pl.when(run)
    def _block():
        q, _, p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, bq, bk, scale,
                               causal)
        do = do_ref[0, :, :].astype(jnp.float32)        # [bq, D]
        v = v_ref[0, :, :].astype(jnp.float32)          # [bk, D]
        delta = _row_stat(delta_ref)                    # [bq, 1]
        dv_scr[:, :] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * jnp.float32(scale)
        dk_scr[:, :] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, :, :] = dk_scr[:, :].astype(dk_ref.dtype)
        dv_ref[0, :, :] = dv_scr[:, :].astype(dv_ref.dtype)


def flash_attention_backward(q, k, v, o, lse, do, scale, causal,
                             block_q=256, block_k=256, interpret=False):
    """All array args [BH, S, D] (lse [BH, S] fp32); returns (dq, dk, dv).

    `scale` is the softmax scale of the UNPADDED head dim (the caller pads D
    to a lane multiple; zero columns keep zero gradients automatically).
    """
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0
    nq, nk = Sq // block_q, Sk // block_k

    # delta[b, i] = rowsum(dO ∘ O): one fused elementwise+reduce in XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    # lane-broadcast the per-row stats so their blocks satisfy (8,128) tiling
    lse_b = jnp.broadcast_to(lse.astype(jnp.float32)[:, :, None],
                             (BH, Sq, LANES))
    delta_b = jnp.broadcast_to(delta[:, :, None], (BH, Sq, LANES))

    common = dict(causal=causal, bq=block_q, bk=block_k, scale=scale)
    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    def row_spec(index_map):
        return pl.BlockSpec((1, block_q, LANES), index_map)

    with jax.enable_x64(False):
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, nk=nk, **common),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            grid=(BH, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                row_spec(lambda b, i, j: (b, i, 0)),
                row_spec(lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            compiler_params=params,
            interpret=interpret,
        )(q, k, v, do, lse_b, delta_b)

        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, nq=nq, **common),
            out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)),
            grid=(BH, nk, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                row_spec(lambda b, j, i: (b, i, 0)),
                row_spec(lambda b, j, i: (b, i, 0)),
            ],
            out_specs=(
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            ),
            scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), jnp.float32)],
            compiler_params=params,
            interpret=interpret,
        )(q, k, v, do, lse_b, delta_b)
    return dq, dk, dv
