"""Pallas TPU flash-attention backward kernels.

Standard flash-attention backward (Dao et al.) mapped to the TPU memory
hierarchy: the forward saves only O and the per-row logsumexp (LSE); the
backward recomputes score blocks on the MXU in fp32 and accumulates dQ (one
kernel, k-sweep in VMEM scratch) and dK/dV (one kernel, q-sweep in VMEM
scratch). Nothing S×S ever touches HBM, and causal off-diagonal blocks are
skipped via predicated grid steps — same blocking discipline as the forward
kernel in flash_attention.py.

Score recomputation applies the same bias / segment-id masking as the
forward, and dropout regenerates bit-identical keep masks by seeding the TPU
PRNG with the same (batch·head, q-block, k-block) triple the forward used.
With dropout, ``dP = keep/(1-p) * (dO·Vᵀ)`` and ``dV += (keep/(1-p)*P)ᵀ·dO``
(the softmax-backward identity ``Σ_k P dP = rowsum(dO∘O)`` still holds since
O was produced by the dropped probabilities).

Per-row vectors (LSE, delta) are fed lane-broadcast as (BH, Sq, 128) tiles —
Mosaic's (8,128) tiling rule forbids a (1, block_q) block over a (BH, Sq)
array — and reduced back to [bq, 1] inside the kernel with a lane-max (all
lanes equal).

Replaces the reference's fused CUDA flash_attn_grad kernel (ref: paddle/phi/
kernels/gpu/flash_attn_grad_kernel.cu capability).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
LANES = 128
SUBLANES = 8


def dropout_keep(seed, b, qi, ki, shape, dropout_p):
    """Regenerable per-block keep mask: seed the TPU PRNG with the grid
    coordinates so forward and both backward kernels draw identical bits.

    Mosaic accepts at most TWO prng_seed operands on current runtimes
    ("Setting seed with more than 2 values is not supported"), so the
    three grid coordinates are packed into one word: q/k block indices
    stay < 2^10 for every supported seq/block combination, and the
    batch*heads index wrapping at 2^11 only makes distant blocks reuse a
    mask stream — deterministic, and identical in fwd and bwd."""
    pltpu.prng_seed(seed, (b << 20) + (qi << 10) + ki)
    bits = pltpu.prng_random_bits(shape)  # int32
    threshold = jnp.int32(
        jnp.iinfo(jnp.int32).min + dropout_p * 2.0 ** 32)
    return bits >= threshold


def segment_mask(qseg_ref, kseg_ref, bq, bk):
    """[bq, bk] bool mask from lane-broadcast q ids (block [1, bq, LANES])
    and sublane-broadcast kv ids (block [1, SUBLANES, bk])."""
    assert bk % LANES == 0, f"block_k={bk} must be a multiple of {LANES}"
    qs = jnp.tile(qseg_ref[0, :, :], (1, bk // LANES))   # [bq, bk]
    ks = kseg_ref[0, :1, :]                              # [1, bk]
    return qs == ks


def _row_stat(ref):
    """Collapse a lane-broadcast [bq, LANES] block to [bq, 1] (lanes equal)."""
    return jnp.max(ref[0, :, :].astype(jnp.float32), axis=1, keepdims=True)


def _parse_refs(refs, has_bias, has_seg, dropout_p, n_out):
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    q_ref, k_ref, v_ref, do_ref = refs[:4]
    refs = refs[4:]
    ab_ref = refs.pop(0) if has_bias else None
    qseg_ref = refs.pop(0) if has_seg else None
    kseg_ref = refs.pop(0) if has_seg else None
    lse_ref, delta_ref = refs[:2]
    outs = refs[2:2 + n_out]
    scratch = refs[2 + n_out:]
    return (seed_ref, q_ref, k_ref, v_ref, do_ref, ab_ref, qseg_ref, kseg_ref,
            lse_ref, delta_ref, outs, scratch)


def _recompute_p(q_ref, k_ref, lse_ref, ab_ref, qseg_ref, kseg_ref,
                 qi, ki, bq, bk, scale, causal):
    q = q_ref[0, :, :].astype(jnp.float32)              # [bq, D]
    k = k_ref[0, :, :].astype(jnp.float32)              # [bk, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    if ab_ref is not None:
        s = s + ab_ref[0, 0, :, :].astype(jnp.float32)
    if qseg_ref is not None:
        s = jnp.where(segment_mask(qseg_ref, kseg_ref, bq, bk), s,
                      jnp.float32(_NEG_INF))
    if causal:
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(k_pos <= q_pos, s, jnp.float32(_NEG_INF))
    lse = _row_stat(lse_ref)                            # [bq, 1]
    return q, k, jnp.exp(s - lse)                       # p: [bq, bk]


def _dq_kernel(*refs, causal, nk, bq, bk, scale, dropout_p, has_bias,
               has_seg):
    (seed_ref, q_ref, k_ref, v_ref, do_ref, ab_ref, qseg_ref, kseg_ref,
     lse_ref, delta_ref, (dq_ref,), (dq_scr,)) = _parse_refs(
        refs, has_bias, has_seg, dropout_p, 1)
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = (ki * bk < (qi + 1) * bq) if causal else (ki >= 0)

    @pl.when(run)
    def _block():
        _, k, p = _recompute_p(q_ref, k_ref, lse_ref, ab_ref, qseg_ref,
                               kseg_ref, qi, ki, bq, bk, scale, causal)
        do = do_ref[0, :, :].astype(jnp.float32)        # [bq, D]
        v = v_ref[0, :, :].astype(jnp.float32)          # [bk, D]
        delta = _row_stat(delta_ref)                    # [bq, 1]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = dropout_keep(seed_ref[0], b, qi, ki, (bq, bk), dropout_p)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta) * jnp.float32(scale)
        dq_scr[:, :] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, :, :] = dq_scr[:, :].astype(dq_ref.dtype)


def _dkv_kernel(*refs, causal, nq, bq, bk, scale, dropout_p, has_bias,
                has_seg):
    (seed_ref, q_ref, k_ref, v_ref, do_ref, ab_ref, qseg_ref, kseg_ref,
     lse_ref, delta_ref, (dk_ref, dv_ref), (dk_scr, dv_scr)) = _parse_refs(
        refs, has_bias, has_seg, dropout_p, 2)
    b = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # causal: q-block contributes to this k-block only when it reaches the
    # diagonal ((qi+1)*bq > ki*bk)
    run = ((qi + 1) * bq > ki * bk) if causal else (qi >= 0)

    @pl.when(run)
    def _block():
        q, _, p = _recompute_p(q_ref, k_ref, lse_ref, ab_ref, qseg_ref,
                               kseg_ref, qi, ki, bq, bk, scale, causal)
        do = do_ref[0, :, :].astype(jnp.float32)        # [bq, D]
        v = v_ref[0, :, :].astype(jnp.float32)          # [bk, D]
        delta = _row_stat(delta_ref)                    # [bq, 1]
        if dropout_p > 0.0:
            keep = dropout_keep(seed_ref[0], b, qi, ki, (bq, bk), dropout_p)
            p_drop = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        else:
            p_drop = p
        dv_scr[:, :] += jax.lax.dot_general(
            p_drop, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta) * jnp.float32(scale)
        dk_scr[:, :] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, :, :] = dk_scr[:, :].astype(dk_ref.dtype)
        dv_ref[0, :, :] = dv_scr[:, :].astype(dv_ref.dtype)


def flash_attention_backward(q, k, v, o, lse, do, scale, causal,
                             block_q=256, block_k=256, interpret=False,
                             bias=None, segment_ids=None, num_heads=1,
                             dropout_p=0.0, dropout_seed=None):
    """All array args [BH, S, D] (lse [BH, S] fp32); returns (dq, dk, dv).

    `scale` is the softmax scale of the UNPADDED head dim (the caller pads D
    to a lane multiple; zero columns keep zero gradients automatically).
    bias is (B|1, H|1, Sq, Sk); segment_ids ((B, Sq), (B, Sk)); num_heads
    maps the flattened BH grid index back to (batch, head) for both.
    """
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0
    nq, nk = Sq // block_q, Sk // block_k
    H = num_heads
    has_bias = bias is not None
    has_seg = segment_ids is not None
    dropout_p = float(dropout_p)
    if dropout_p > 0.0:
        # dropout_keep packs (b, qi, ki) into ONE prng_seed word as
        # (b<<20)+(qi<<10)+ki: block indices at or above 2^10 would silently
        # alias seed bits and correlate keep masks across blocks. Grid dims
        # are static at trace time, so enforce the packing envelope here.
        assert nq < 1024 and nk < 1024, (
            f"flash-attention dropout PRNG seed packs q/k block indices into "
            f"10 bits each; got num_q_blocks={nq}, num_k_blocks={nk} "
            f"(seq_len/block size too large) — raise block_q/block_k so both "
            f"stay below 1024")

    # delta[b, i] = rowsum(dO ∘ O): one fused elementwise+reduce in XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    # lane-broadcast the per-row stats so their blocks satisfy (8,128) tiling
    lse_b = jnp.broadcast_to(lse.astype(jnp.float32)[:, :, None],
                             (BH, Sq, LANES))
    delta_b = jnp.broadcast_to(delta[:, :, None], (BH, Sq, LANES))

    common = dict(causal=causal, bq=block_q, bk=block_k, scale=scale,
                  dropout_p=dropout_p, has_bias=has_bias, has_seg=has_seg)
    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    def shared_operands():
        """(operands, spec-builders) for the inputs both kernels share; each
        spec builder takes (bmap, imap, jmap) index functions where i indexes
        q-blocks and j indexes k-blocks."""
        ops, builders = [], []
        if dropout_p > 0.0:
            assert dropout_seed is not None
            ops.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
            builders.append(lambda qm, km: pl.BlockSpec(
                memory_space=pltpu.SMEM))
        ops += [q, k, v, do]
        builders += [
            lambda qm, km: pl.BlockSpec((1, block_q, D),
                                        lambda *g: (g[0], qm(*g), 0)),
            lambda qm, km: pl.BlockSpec((1, block_k, D),
                                        lambda *g: (g[0], km(*g), 0)),
            lambda qm, km: pl.BlockSpec((1, block_k, D),
                                        lambda *g: (g[0], km(*g), 0)),
            lambda qm, km: pl.BlockSpec((1, block_q, D),
                                        lambda *g: (g[0], qm(*g), 0)),
        ]
        if has_bias:
            Bb, Hb = bias.shape[:2]
            ops.append(bias)
            builders.append(lambda qm, km: pl.BlockSpec(
                (1, 1, block_q, block_k),
                lambda *g: (0 if Bb == 1 else g[0] // H,
                            0 if Hb == 1 else g[0] % H, qm(*g), km(*g))))
        if has_seg:
            qs, ks = segment_ids
            B = qs.shape[0]
            ops.append(jax.lax.broadcast_in_dim(
                qs.astype(jnp.int32), (B, Sq, LANES), (0, 1)))
            builders.append(lambda qm, km: pl.BlockSpec(
                (1, block_q, LANES), lambda *g: (g[0] // H, qm(*g), 0)))
            ops.append(jax.lax.broadcast_in_dim(
                ks.astype(jnp.int32), (B, SUBLANES, Sk), (0, 2)))
            builders.append(lambda qm, km: pl.BlockSpec(
                (1, SUBLANES, block_k), lambda *g: (g[0] // H, 0, km(*g))))
        ops += [lse_b, delta_b]
        builders += [
            lambda qm, km: pl.BlockSpec((1, block_q, LANES),
                                        lambda *g: (g[0], qm(*g), 0)),
            lambda qm, km: pl.BlockSpec((1, block_q, LANES),
                                        lambda *g: (g[0], qm(*g), 0)),
        ]
        return ops, builders

    with jax.enable_x64(False):
        # dQ: grid (BH, q-block, k-block); k is the reduction (arbitrary) dim
        ops, builders = shared_operands()
        qm, km = (lambda b, i, j: i), (lambda b, i, j: j)
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, nk=nk, **common),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            grid=(BH, nq, nk),
            in_specs=[mk(qm, km) for mk in builders],
            out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            compiler_params=params,
            interpret=interpret,
        )(*ops)

        # dK/dV: grid (BH, k-block, q-block); q is the reduction dim
        ops, builders = shared_operands()
        qm, km = (lambda b, j, i: i), (lambda b, j, i: j)
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, nq=nq, **common),
            out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)),
            grid=(BH, nk, nq),
            in_specs=[mk(qm, km) for mk in builders],
            out_specs=(
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            ),
            scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), jnp.float32)],
            compiler_params=params,
            interpret=interpret,
        )(*ops)
    return dq, dk, dv
