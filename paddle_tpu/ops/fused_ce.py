"""Fused LM-head + softmax cross-entropy, chunked over the vocab axis.

Capability target: the reference's fused ``softmax_with_cross_entropy``
(ref: python/paddle/nn/functional/loss.py — its CUDA kernel never
materializes the fp32 softmax). On TPU we go one step further and fuse the
LM-head matmul into the loss too: the fp32 ``[N, V]`` logits buffer never
exists. Forward runs an online logsumexp over vocab chunks
(flash-attention-style running max/sum); backward recomputes each chunk's
logits and applies ``(softmax - onehot) * g`` chunk by chunk.

Why it matters: GPT-3 1.3B at bs=8, seq=2048, V≈50k needs ~3.2 GB for one
fp32 logits buffer (plus the bf16 original and its gradient) — enough to OOM
a 16 GB chip before the model itself is counted. Chunked, the transient is
``O(N * V / num_chunks)``.

All matmuls run in the input dtype (bf16 on TPU → MXU) with fp32
accumulation via ``preferred_element_type``; the online statistics are fp32.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _chunking(V: int, num_chunks: int):
    """Pick a chunk width that is a multiple of 128 (TPU lane width) and
    covers V in <= num_chunks chunks."""
    c = -(-V // max(num_chunks, 1))
    c = -(-c // 128) * 128 if V >= 128 else c
    n = -(-V // c)
    return c, n


def _fwd_stats(hidden, head_w, labels, num_chunks, head_b=None):
    """Online logsumexp + gold-logit gather over vocab chunks.

    hidden: [N, H] (any float dtype), head_w: [H, V], labels: [N] int,
    head_b: optional [V] bias (BERT's mlm_head has one; GPT heads don't).
    Returns (logz [N] fp32, gold [N] fp32).
    """
    N, H = hidden.shape
    V = head_w.shape[1]
    C, n = _chunking(V, num_chunks)
    pad = C * n - V
    wpad = jnp.pad(head_w, ((0, 0), (0, pad))) if pad else head_w
    bpad = None
    if head_b is not None:
        bpad = jnp.pad(head_b, (0, pad)) if pad else head_b
    f32 = jnp.float32

    def body(carry, c):
        m, s, gold = carry
        start = c * C
        w_c = jax.lax.dynamic_slice(wpad, (0, start), (H, C))
        logits = jnp.dot(hidden, w_c, preferred_element_type=f32)
        if bpad is not None:
            logits = logits + jax.lax.dynamic_slice(
                bpad, (start,), (C,)).astype(f32)[None, :]
        col = start + jax.lax.iota(jnp.int32, C)[None, :]
        logits = jnp.where(col < V, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        idx = jnp.clip(labels - start, 0, C - 1)
        g = jnp.take_along_axis(logits, idx[:, None], axis=-1)[:, 0]
        in_c = (labels >= start) & (labels < start + C)
        gold = jnp.where(in_c, g, gold)
        return (m_new, s, gold), None

    init = (jnp.full((N,), -jnp.inf, f32), jnp.zeros((N,), f32),
            jnp.zeros((N,), f32))
    (m, s, gold), _ = jax.lax.scan(body, init, jnp.arange(n))
    return m + jnp.log(s), gold


def fused_linear_cross_entropy(hidden, head_w, labels, num_chunks=8,
                               head_b=None):
    """Per-token CE of ``softmax(hidden @ head_w [+ head_b])`` vs ``labels``
    without materializing the logits. Returns losses ``[N]`` (fp32); callers
    apply their own mask/reduction (so ignore_index is a caller-side
    ``where``).
    """
    if head_b is None:
        return _fce(hidden, head_w, labels, num_chunks)
    return _fce_bias(hidden, head_w, head_b, labels, num_chunks)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fce(hidden, head_w, labels, num_chunks=8):
    logz, gold = _fwd_stats(hidden, head_w, labels, num_chunks)
    return logz - gold


def _fce_fwd(hidden, head_w, labels, num_chunks):
    logz, gold = _fwd_stats(hidden, head_w, labels, num_chunks)
    return logz - gold, (hidden, head_w, labels, logz)


def _fce_bwd(num_chunks, res, g):
    hidden, head_w, labels, logz = res
    N, H = hidden.shape
    V = head_w.shape[1]
    C, n = _chunking(V, num_chunks)
    pad = C * n - V
    wpad = jnp.pad(head_w, ((0, 0), (0, pad))) if pad else head_w
    f32 = jnp.float32

    def body(carry, c):
        dh, dW = carry
        start = c * C
        w_c = jax.lax.dynamic_slice(wpad, (0, start), (H, C))
        logits = jnp.dot(hidden, w_c, preferred_element_type=f32)
        col = start + jax.lax.iota(jnp.int32, C)[None, :]
        p = jnp.where(col < V, jnp.exp(logits - logz[:, None]), 0.0)
        delta = (p - (col == labels[:, None]).astype(f32)) * g[:, None]
        # cast to the compute dtype for the MXU; accumulate fp32
        dc = delta.astype(hidden.dtype)
        dh = dh + jnp.dot(dc, w_c.T, preferred_element_type=f32)
        dw_c = jnp.dot(hidden.T, dc, preferred_element_type=f32)
        dW = jax.lax.dynamic_update_slice(dW, dw_c, (0, start))
        return (dh, dW), None

    init = (jnp.zeros((N, H), f32), jnp.zeros((H, C * n), f32))
    (dh, dW), _ = jax.lax.scan(body, init, jnp.arange(n))
    if pad:
        dW = dW[:, :V]
    return dh.astype(hidden.dtype), dW.astype(head_w.dtype), None


_fce.defvjp(_fce_fwd, _fce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fce_bias(hidden, head_w, head_b, labels, num_chunks=8):
    logz, gold = _fwd_stats(hidden, head_w, labels, num_chunks, head_b)
    return logz - gold


def _fceb_fwd(hidden, head_w, head_b, labels, num_chunks):
    logz, gold = _fwd_stats(hidden, head_w, labels, num_chunks, head_b)
    return logz - gold, (hidden, head_w, head_b, labels, logz)


def _fceb_bwd(num_chunks, res, g):
    hidden, head_w, head_b, labels, logz = res
    N, H = hidden.shape
    V = head_w.shape[1]
    C, n = _chunking(V, num_chunks)
    pad = C * n - V
    wpad = jnp.pad(head_w, ((0, 0), (0, pad))) if pad else head_w
    bpad = jnp.pad(head_b, (0, pad)) if pad else head_b
    f32 = jnp.float32

    def body(carry, c):
        dh, dW, dB = carry
        start = c * C
        w_c = jax.lax.dynamic_slice(wpad, (0, start), (H, C))
        b_c = jax.lax.dynamic_slice(bpad, (start,), (C,)).astype(f32)
        logits = jnp.dot(hidden, w_c, preferred_element_type=f32) + b_c[None, :]
        col = start + jax.lax.iota(jnp.int32, C)[None, :]
        p = jnp.where(col < V, jnp.exp(logits - logz[:, None]), 0.0)
        delta = (p - (col == labels[:, None]).astype(f32)) * g[:, None]
        dc = delta.astype(hidden.dtype)
        dh = dh + jnp.dot(dc, w_c.T, preferred_element_type=f32)
        dw_c = jnp.dot(hidden.T, dc, preferred_element_type=f32)
        dW = jax.lax.dynamic_update_slice(dW, dw_c, (0, start))
        dB = jax.lax.dynamic_update_slice(dB, jnp.sum(delta, axis=0), (start,))
        return (dh, dW, dB), None

    init = (jnp.zeros((N, H), f32), jnp.zeros((H, C * n), f32),
            jnp.zeros((C * n,), f32))
    (dh, dW, dB), _ = jax.lax.scan(body, init, jnp.arange(n))
    if pad:
        dW = dW[:, :V]
        dB = dB[:V]
    return (dh.astype(hidden.dtype), dW.astype(head_w.dtype),
            dB.astype(head_b.dtype), None)


_fce_bias.defvjp(_fceb_fwd, _fceb_bwd)


def fused_lm_loss(hidden, head_w, ids, num_chunks=8, shift=True):
    """Mean next-token LM loss straight from final hidden states.

    hidden: [B, S, H]; head_w: [H, V]; ids: [B, S]. With ``shift``, positions
    predict their successor (standard causal LM).
    """
    if shift:
        hidden = hidden[:, :-1]
        labels = ids[:, 1:]
    else:
        labels = ids
    B, S, H = hidden.shape
    losses = fused_linear_cross_entropy(
        hidden.reshape(B * S, H), head_w,
        labels.reshape(-1).astype(jnp.int32), num_chunks)
    return jnp.mean(losses)
