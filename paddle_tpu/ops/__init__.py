"""Op library: pallas kernels, fused compositions, custom-op registry."""
from .custom import (  # noqa: F401
    register_custom_op, get_custom_op, list_custom_ops, deregister_custom_op,
    CustomOp,
)
