"""Custom-op registration — the TPU-native analog of the reference's
custom-operator machinery (ref: python/paddle/utils/cpp_extension/
cpp_extension.py:79 setup(), paddle/fluid/framework/custom_operator.cc).

The reference compiles user C++/CUDA kernels and registers them with the
operator registry (+ optional PD_BUILD_GRAD_OP backward). Here the kernel
language for device code is jax/pallas, so registration is a Python-level
affair: `register_custom_op` installs a user kernel (any jax-traceable
callable — typically a `pallas_call`) into the dispatch table so it

  * dispatches through `dispatch.apply` (eager tape autograd, AMP casting),
  * composes with `jit.to_static` / `TrainStep` (it is ordinary traceable
    jax inside),
  * carries a user backward via `jax.custom_vjp` when `vjp_fwd`/`vjp_bwd`
    are given (the PD_BUILD_GRAD_OP analog) — otherwise jax autodiff
    differentiates through the kernel body.

Host-side (CPU) custom ops — the literal C++ path — live in
`paddle_tpu.utils.cpp_extension.load`, which compiles C++ sources with g++
and binds them via ctypes (the reference's JIT `load()` analog).

Example::

    import jax.numpy as jnp
    from paddle_tpu.ops.custom import register_custom_op

    @register_custom_op("fused_scale_tanh", amp="white")
    def fused_scale_tanh(x, scale=2.0):
        return jnp.tanh(x) * scale          # or a pl.pallas_call(...)

    y = fused_scale_tanh(tensor)            # Tensor in, Tensor out, taped
"""
from __future__ import annotations

import jax

from ..dispatch import apply as _apply, WHITE_OPS, BLACK_OPS

_REGISTRY: dict[str, "CustomOp"] = {}


class CustomOp:
    """A registered custom op: callable on Tensors, recorded on the tape."""

    def __init__(self, name, fn, vjp_fwd=None, vjp_bwd=None, amp=None,
                 nondiff_argnums=()):
        if (vjp_fwd is None) != (vjp_bwd is None):
            raise ValueError("vjp_fwd and vjp_bwd must be given together")
        self.name = name
        self.raw_fn = fn
        self.has_custom_vjp = vjp_fwd is not None
        if self.has_custom_vjp:
            cv = jax.custom_vjp(fn, nondiff_argnums=tuple(nondiff_argnums))
            cv.defvjp(vjp_fwd, vjp_bwd)
            self.fn = cv
        else:
            self.fn = fn
        if amp == "white":
            WHITE_OPS.add(name)
        elif amp == "black":
            BLACK_OPS.add(name)
        elif amp not in (None, "auto"):
            raise ValueError(f"amp must be 'white', 'black' or None, "
                             f"got {amp!r}")
        self.amp = amp

    def __call__(self, *inputs, **static_kw):
        return _apply(self.fn, *inputs, op_name=self.name, **static_kw)

    def __repr__(self):
        grad = "custom_vjp" if self.has_custom_vjp else "autodiff"
        return f"<CustomOp {self.name} ({grad})>"


def register_custom_op(name, fn=None, *, vjp_fwd=None, vjp_bwd=None,
                       amp=None, nondiff_argnums=(), overwrite=False):
    """Register `fn` (jax arrays in/out) as op `name`. Usable directly or as
    a decorator. Returns the CustomOp callable (Tensors in/out).

    vjp_fwd(x...) -> (out, residuals) and vjp_bwd(residuals, cotangent) ->
    grads follow `jax.custom_vjp` conventions. amp='white' computes in the
    autocast dtype (MXU ops), amp='black' forces fp32 (numerics)."""
    def _register(f):
        if name in _REGISTRY and not overwrite:
            raise ValueError(
                f"custom op {name!r} already registered; pass overwrite=True "
                f"to replace it")
        op = CustomOp(name, f, vjp_fwd=vjp_fwd, vjp_bwd=vjp_bwd, amp=amp,
                      nondiff_argnums=nondiff_argnums)
        _REGISTRY[name] = op
        return op

    if fn is not None:
        return _register(fn)
    return _register


def get_custom_op(name):
    """Look up a registered op by name (KeyError if absent)."""
    return _REGISTRY[name]


def list_custom_ops():
    return sorted(_REGISTRY)


def deregister_custom_op(name):
    op = _REGISTRY.pop(name, None)
    if op is not None:
        WHITE_OPS.discard(name)
        BLACK_OPS.discard(name)
    return op
