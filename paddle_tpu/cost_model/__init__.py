"""Cost model (ref: python/paddle/cost_model/cost_model.py).

The reference profiles static Programs per-op and ships a benchmark table
(static_op_benchmark.json). TPU-native: the "program" is a jitted function
and XLA's compiled cost analysis IS the cost model — `static_cost_data`
returns the compiler's FLOP/byte estimates, `profile_measure` runs the
executable and reports measured wall time alongside them.
"""
from __future__ import annotations

import time

import jax


class CostModel:
    def __init__(self):
        self._analysis = None

    def build_program(self, fn=None, example_args=()):
        """Register the jittable fn to analyze (the reference builds a demo
        fc Program when called with no args; we require the real fn)."""
        if fn is None:
            raise ValueError("pass the jittable fn to analyze: "
                             "build_program(fn, example_args)")
        self._fn = fn
        self._args = example_args
        self._lowered = jax.jit(fn).lower(*example_args)
        return self._lowered

    def static_cost_data(self):
        """XLA's compile-time cost analysis: flops, bytes accessed,
        transcendentals (ref static_cost_data, which loads the shipped
        benchmark json)."""
        compiled = self._lowered.compile()
        self._compiled = compiled
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        self._analysis = dict(ca) if ca else {}
        return self._analysis

    def get_static_op_time(self, op_name=None, forward=True, dtype="float32"):
        """Per-metric lookup from the cost analysis (the reference keys a
        benchmark table by op name; XLA reports whole-program metrics)."""
        if self._analysis is None:
            self.static_cost_data()
        if op_name is None:
            return self._analysis
        return {k: v for k, v in self._analysis.items() if op_name in k}

    def profile_measure(self, steps=10, warmup=2):
        """Execute and measure (ref profile_measure runs the Program under
        the profiler). Returns seconds/step plus the static analysis."""
        if self._analysis is None:
            self.static_cost_data()
        compiled = self._compiled
        out = None
        for _ in range(warmup):
            out = compiled(*self._args)
        if out is not None:
            jax.device_get(jax.tree_util.tree_leaves(out)[0])
        t0 = time.perf_counter()
        for _ in range(steps):
            out = compiled(*self._args)
        # device_get, not block_until_ready: remote platforms may not block
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
        dt = (time.perf_counter() - t0) / steps
        return {"time_per_step_s": dt, **(self._analysis or {})}
