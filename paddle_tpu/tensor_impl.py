"""Eager Tensor for paddle_tpu.

TPU-native re-design of the reference dygraph Tensor (ref: paddle/fluid/eager,
python/paddle/fluid/dygraph/varbase_patch_methods.py). The Tensor wraps a
jax.Array; eager ops dispatch through `paddle_tpu.dispatch.apply`, which both
executes on-device via XLA and (when grads are needed) records a tape node
holding the `jax.vjp` pullback. `.backward()` walks that tape.

Unlike the reference there are no views/strides: XLA arrays are immutable, so
"in-place" methods rebind `_data` on the same Python object (semantically
equivalent for the supported API surface; true aliasing is not exposed).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .framework import state as _st


class Tensor:
    __slots__ = (
        "_data", "stop_gradient", "_grad", "_node", "_out_idx", "name",
        "persistable", "_placeholder", "_leaf_hooks", "__weakref__",
        # auto_parallel distribution metadata (ref: dist tensor attrs)
        "dist_spec", "placements", "process_mesh", "_partial_stack",
    )

    _name_counter = 0

    def __init__(self, data, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array) and not _is_tracer(data):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node = None
        self._out_idx = 0
        # Auto-generated unique names match paddle's generated_tensor_N
        # convention and keep optimizer state_dict keys collision-free.
        if name is None:
            name = f"generated_tensor_{Tensor._name_counter}"
            Tensor._name_counter += 1
        self.name = name
        self.persistable = False
        self._placeholder = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def place(self):
        from .framework import device as _dev
        try:
            devs = getattr(self._data, "devices", None)
            if devs:
                d = next(iter(devs()))
                return _dev.Place(d.platform, d.id)
        except Exception:
            pass
        return _dev.Place(jax.default_backend(), 0)

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    @property
    def is_leaf(self):
        return self._node is None

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        prefix = "Parameter" if isinstance(self, Parameter) else "Tensor"
        try:
            body = np.array2string(np.asarray(self._data), precision=8, separator=", ")
        except Exception:  # tracers
            body = repr(self._data)
        return (f"{prefix}(shape={self.shape}, dtype={self._data.dtype}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    # -- conversions --------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        arr = np.asarray(self._data)
        return arr.item(*args) if args else arr.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __float__(self):
        return float(np.asarray(self._data))

    def __int__(self):
        return int(np.asarray(self._data))

    def __bool__(self):
        return bool(np.asarray(self._data))

    def __index__(self):
        return int(np.asarray(self._data))

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *a, **k):
        return self._data.__dlpack__(*a, **k)

    def __jax_array__(self):
        return self._data

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .autograd import engine
        engine.backward(self, grad_tensor, retain_graph)

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._data))
        else:
            self._grad = None

    clear_grad = clear_gradient

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def register_hook(self, hook):
        from .autograd import engine
        return engine.register_tensor_hook(self, hook)

    # -- in-place -----------------------------------------------------------
    def set_value(self, value):
        """In-place rebind; shape must match (ref Tensor.set_value semantics)."""
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch {tuple(value.shape)} vs {tuple(self._data.shape)}")
        self._data = value.astype(self._data.dtype)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def _rebind(self, new_data, node=None, out_idx=0):
        """Internal: rebind after an in-place differentiable op."""
        self._data = new_data
        self._node = node
        self._out_idx = out_idx
        return self

    # -- misc parity helpers -------------------------------------------------
    def clone(self):
        from .dispatch import apply
        return apply(lambda x: x + jnp.zeros((), x.dtype), self, op_name="clone")

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        # supports .to(dtype) / .to("tpu") minimal parity
        from .framework.state import to_jnp_dtype
        for a in args:
            if isinstance(a, str) and a.lower() in ("cpu", "tpu", "gpu"):
                continue
            d = to_jnp_dtype(a)
            if d is not None:
                return self.astype(d)
        if "dtype" in kwargs:
            return self.astype(kwargs["dtype"])
        return self

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    @property
    def T(self):
        from .tensor import linalg
        return linalg.t(self)

    @property
    def mT(self):
        from .tensor import manipulation as m
        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return m.transpose(self, perm)

    # Arithmetic dunders and tensor methods are attached by
    # paddle_tpu.tensor._install_tensor_methods() to avoid circular imports.


class Parameter(Tensor):
    __slots__ = ("trainable", "regularizer", "need_clip",
                 "is_distributed", "optimize_attr", "no_sync")

    _name_counter = [0]

    def __init__(self, data, name=None, trainable=True, regularizer=None,
                 need_clip=True, dist_spec=None):
        if name is None:
            name = f"param_{Parameter._name_counter[0]}"
            Parameter._name_counter[0] += 1
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.regularizer = regularizer
        self.need_clip = need_clip
        # Optional jax PartitionSpec for GSPMD placement (set by parallel layers)
        self.dist_spec = dist_spec
        self.is_distributed = False
        self.optimize_attr = {"learning_rate": 1.0}
        self.no_sync = False
        self.persistable = True


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def as_tensor_data(x):
    """Unwrap Tensor -> jax array; pass through scalars/arrays."""
    return x._data if isinstance(x, Tensor) else x


def wrap(data, stop_gradient=True):
    return Tensor(data, stop_gradient=stop_gradient)
