"""Text domain API (ref: python/paddle/text/__init__.py, viterbi_decode.py,
datasets/*).

`viterbi_decode` is TPU-native: the forward max-sum recursion and the
backtrace are both `lax.scan` loops over the time axis (static trip count,
variable lengths handled by masking), so decode jits to a single XLA program
instead of the reference's dedicated C++ kernel.

Datasets mirror the reference's loaders; in zero-egress environments they
fall back to deterministic synthetic corpora with the right shapes/vocab
(same pattern as paddle_tpu.vision.datasets).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..dispatch import apply
from ..io import Dataset
from ..nn import Layer
from ..tensor_impl import Tensor, as_tensor_data

__all__ = [
    "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
    "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode",
]


def _viterbi_impl(pot, trans, lengths, include_bos_eos_tag):
    """pot (B,L,C) f32/f64, trans (C,C), lengths (B,) int → scores (B,), paths (B,L)."""
    B, L, C = pot.shape
    lengths = lengths.astype(jnp.int32)
    if include_bos_eos_tag:
        start_idx, stop_idx = C - 1, C - 2
        alpha = pot[:, 0] + trans[start_idx][None, :]
    else:
        alpha = pot[:, 0]

    def fwd(alpha, inp):
        t, pot_t = inp
        # score[b, i, j] = alpha[b, i] + trans[i, j]
        score = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(score, axis=1)                  # (B, C)
        next_alpha = jnp.max(score, axis=1) + pot_t            # (B, C)
        live = (t < lengths)[:, None]
        return jnp.where(live, next_alpha, alpha), best_prev

    ts = jnp.arange(1, L)
    alpha, bps = lax.scan(fwd, alpha, (ts, jnp.moveaxis(pot[:, 1:], 1, 0)))
    # bps: (L-1, B, C), bps[t-1][b, j] = best tag at t-1 given tag j at t
    if include_bos_eos_tag:
        alpha = alpha + trans[:, stop_idx][None, :]
    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)     # tag at len-1

    def bwd(carry, inp):
        t, bp_t = inp                                          # bp for step t
        cur = jnp.where(t == lengths - 1, last_tag, carry)     # tag at pos t
        prev = jnp.take_along_axis(bp_t, cur[:, None], axis=1)[:, 0]
        emit = jnp.where(t < lengths, cur, 0)
        return prev.astype(jnp.int32), emit

    if L > 1:
        carry, emits = lax.scan(bwd, last_tag,
                                (ts[::-1], bps[::-1]))         # t = L-1 .. 1
        tag0 = jnp.where(0 == lengths - 1, last_tag, carry)
        paths = jnp.concatenate([tag0[:, None], emits[::-1].T], axis=1)
    else:
        paths = last_tag[:, None]
    paths = jnp.where(jnp.arange(L)[None, :] < lengths[:, None], paths, 0)
    return scores, paths.astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence under unary potentials + transitions.

    Returns (scores [B], paths [B, max(lengths)]). With include_bos_eos_tag,
    the last/second-to-last tag indices act as BOS/EOS as in the reference
    C++ kernel (ref: paddle/phi/kernels/cpu/viterbi_decode_kernel.cc).
    """
    scores, paths = apply(_viterbi_impl, potentials, transition_params, lengths,
                          include_bos_eos_tag=bool(include_bos_eos_tag))
    max_len = int(np.asarray(jax.device_get(as_tensor_data(lengths))).max())
    return scores, paths[:, :max_len]


class ViterbiDecoder(Layer):
    """ref: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# -- datasets (synthetic fallback; see module docstring) ---------------------

class _SyntheticTextDataset(Dataset):
    _SEED = {"train": 1, "test": 2, "dev": 3, "gen": 4}

    def __init__(self, mode, size):
        self.mode = mode
        self._rng = np.random.RandomState(self._SEED.get(mode, 9))
        self._size = size

    def __len__(self):
        return self._size


class Imdb(_SyntheticTextDataset):
    """Sentiment classification: (token_ids, label∈{0,1})."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        super().__init__(mode, 512)
        self.word_idx = {f"w{i}": i for i in range(5149)}
        self._docs = [self._rng.randint(0, 5149, self._rng.randint(8, 120))
                      .astype(np.int64) for _ in range(self._size)]
        self._labels = self._rng.randint(0, 2, self._size).astype(np.int64)

    def __getitem__(self, idx):
        return self._docs[idx], self._labels[idx]


class Imikolov(_SyntheticTextDataset):
    """N-gram LM dataset: tuples of n token ids."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        super().__init__(mode, 2048)
        self.window_size = window_size
        self.word_idx = {f"w{i}": i for i in range(2074)}
        self._grams = self._rng.randint(0, 2074, (self._size, window_size))

    def __getitem__(self, idx):
        return tuple(np.int64(v) for v in self._grams[idx])


class Movielens(_SyntheticTextDataset):
    """Rating prediction: (user feats..., movie feats..., score)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        super().__init__(mode, 1024)
        self._rows = [
            (np.int64(self._rng.randint(1, 6041)),       # user id
             np.int64(self._rng.randint(0, 2)),          # gender
             np.int64(self._rng.randint(0, 7)),          # age bucket
             np.int64(self._rng.randint(0, 21)),         # job
             np.int64(self._rng.randint(1, 3953)),       # movie id
             self._rng.randint(0, 19, 3).astype(np.int64),   # categories
             self._rng.randint(0, 5175, 4).astype(np.int64),  # title tokens
             np.float32(self._rng.randint(1, 6)))        # score
            for _ in range(self._size)]

    def __getitem__(self, idx):
        return self._rows[idx]


class UCIHousing(_SyntheticTextDataset):
    """Regression: 13 features → price."""

    def __init__(self, data_file=None, mode="train", download=True):
        super().__init__(mode, 404 if mode == "train" else 102)
        self._x = self._rng.randn(self._size, 13).astype(np.float32)
        w = np.linspace(-1, 1, 13, dtype=np.float32)
        self._y = (self._x @ w + 22.5).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]


class _SyntheticTranslation(_SyntheticTextDataset):
    def __init__(self, mode, dict_size):
        super().__init__(mode, 512)
        self.dict_size = dict_size = max(dict_size, 30)
        self._pairs = [
            (self._rng.randint(3, dict_size, self._rng.randint(4, 30)).astype(np.int64),
             self._rng.randint(3, dict_size, self._rng.randint(4, 30)).astype(np.int64))
            for _ in range(self._size)]

    def __getitem__(self, idx):
        src, tgt = self._pairs[idx]
        # (src, trg, trg_next) with <s>=0, <e>=1 as in the reference
        trg = np.concatenate([[0], tgt])
        trg_next = np.concatenate([tgt, [1]])
        return src, trg, trg_next


class WMT14(_SyntheticTranslation):
    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        super().__init__(mode, dict_size)


class WMT16(_SyntheticTranslation):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        super().__init__(mode, src_dict_size)


class Conll05st(_SyntheticTextDataset):
    """SRL sequence labeling rows (word/pred/label id sequences)."""

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, mode="train",
                 download=True):
        super().__init__(mode, 256)
        self._rows = []
        for _ in range(self._size):
            n = self._rng.randint(5, 40)
            words = self._rng.randint(0, 44068, n).astype(np.int64)
            ctx = [self._rng.randint(0, 44068, n).astype(np.int64)
                   for _ in range(5)]
            pred = np.full(n, self._rng.randint(0, 3162), np.int64)
            mark = self._rng.randint(0, 2, n).astype(np.int64)
            label = self._rng.randint(0, 106, n).astype(np.int64)
            self._rows.append((words, *ctx, pred, mark, label))

    def __getitem__(self, idx):
        return self._rows[idx]
