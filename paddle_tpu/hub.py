"""Model hub (ref: python/paddle/hub.py).

Zero-egress environment: `github`/`gitee` sources are unavailable; `local`
source loads a hubconf.py from a directory — same entrypoint contract as the
reference (callables listed in hubconf, `dependencies` checked).
"""
from __future__ import annotations

import importlib.util
import os

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for dep in getattr(mod, "dependencies", []):
        importlib.import_module(dep)
    return mod


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network egress; this build supports "
            f"source='local' (a directory containing {_HUBCONF})")


def list(repo_dir, source="local", force_reload=False):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, *args, source="local", force_reload=False, **kwargs):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model)(*args, **kwargs)
