"""Probability distributions (ref: python/paddle/distribution/*).

Distribution/Normal/Uniform/Categorical/Bernoulli + kl_divergence, built on
jax.random with the framework's global seeded key stream (framework.random),
so `paddle.seed` controls sampling determinism.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor_impl import Tensor, as_tensor_data, wrap
from ..framework.random import next_key


def _arr(x):
    if isinstance(x, (int, float)):
        return jnp.asarray(x, jnp.float32)
    return jnp.asarray(as_tensor_data(x))


class Distribution:
    """Base class (ref distribution/distribution.py)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return wrap(jnp.exp(as_tensor_data(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    """Gaussian (ref distribution/normal.py)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        shape = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(shape)

    @property
    def mean(self):
        return wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return wrap(jnp.broadcast_to(self.scale**2, self.batch_shape))

    @property
    def stddev(self):
        return wrap(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(next_key(), shape, jnp.float32)
        return wrap(self.loc + eps * self.scale)

    def log_prob(self, value):
        v = as_tensor_data(value)
        var = self.scale**2
        return wrap(-((v - self.loc) ** 2) / (2 * var)
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return wrap(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))

    def kl_divergence(self, other):
        assert isinstance(other, Normal)
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Uniform(Distribution):
    """U[low, high) (ref distribution/uniform.py)."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape, jnp.float32)
        return wrap(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = as_tensor_data(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return wrap(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return wrap(jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape))

    def kl_divergence(self, other):
        assert isinstance(other, Uniform)
        return wrap(jnp.log((other.high - other.low) / (self.high - self.low)))


class Categorical(Distribution):
    """Categorical over last axis of logits (ref distribution/categorical.py)."""

    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = as_tensor_data(logits).astype(jnp.float32)
        else:
            self.logits = jnp.log(as_tensor_data(probs).astype(jnp.float32) + 1e-30)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return wrap(jax.nn.softmax(self.logits, axis=-1))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return wrap(jax.random.categorical(next_key(), self.logits, shape=shape))

    def log_prob(self, value):
        v = as_tensor_data(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return wrap(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        p = jnp.exp(logp)
        return wrap(-(p * logp).sum(-1))

    def kl_divergence(self, other):
        assert isinstance(other, Categorical)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        logq = jax.nn.log_softmax(other.logits, axis=-1)
        return wrap((jnp.exp(logp) * (logp - logq)).sum(-1))


class Bernoulli(Distribution):
    """Bernoulli(probs) (ref distribution/bernoulli.py)."""

    def __init__(self, probs, name=None):
        self.probs_ = _arr(probs)
        super().__init__(self.probs_.shape)

    @property
    def mean(self):
        return wrap(self.probs_)

    @property
    def variance(self):
        return wrap(self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape, jnp.float32)
        return wrap((u < self.probs_).astype(jnp.float32))

    def log_prob(self, value):
        v = as_tensor_data(value).astype(jnp.float32)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return wrap(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    def kl_divergence(self, other):
        assert isinstance(other, Bernoulli)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        q = jnp.clip(other.probs_, 1e-7, 1 - 1e-7)
        return wrap(p * (jnp.log(p) - jnp.log(q))
                    + (1 - p) * (jnp.log1p(-p) - jnp.log1p(-q)))


def kl_divergence(p, q):
    """Dispatch KL(p||q) (ref distribution/kl.py)."""
    return p.kl_divergence(q)


from .extras import (  # noqa: E402,F401
    Beta, Cauchy, Dirichlet, ExponentialFamily, Multinomial, Independent,
    TransformedDistribution, Laplace, LogNormal, Gumbel, Geometric,
    register_kl, dispatch_kl as _dispatch_kl,
)


def kl_divergence(p, q):  # noqa: F811 — registry-aware override
    """Dispatch KL(p||q): registered pairs first (`register_kl`), then the
    distribution's own closed form (ref distribution/kl.py)."""
    out = _dispatch_kl(p, q)
    if out is not None:
        return out
    return p.kl_divergence(q)

from . import transform  # noqa: E402,F401
from .transform import (  # noqa: E402,F401
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
)
