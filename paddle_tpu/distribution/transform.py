"""paddle.distribution.transform (ref: python/paddle/distribution/
transform.py): invertible transforms with log-det-jacobians, composable
into TransformedDistribution. Pure jnp math — every transform is
jit/grad-compatible.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data, wrap

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"


def _arr(x):
    return jnp.asarray(as_tensor_data(x))


class Transform:
    _type = Type.INJECTION

    @property
    def type(self):
        return self._type

    def forward(self, x):
        return wrap(self._forward(_arr(x)))

    def inverse(self, y):
        return wrap(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return wrap(self._forward_log_det_jacobian(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _arr(y)
        return wrap(-self._forward_log_det_jacobian(self._inverse(yv)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # subclass surface
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    """y = |x| (surjective; inverse returns the positive branch)."""
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power on the positive half-line."""
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log2 - x - softplus(-2x)), the stable form
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (surjection onto the simplex;
    inverse is log, unique up to an additive constant — ref transform.py
    SoftmaxTransform)."""
    _type = Type.OTHER

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError(
            "SoftmaxTransform is not a bijection; no log-det-jacobian")


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class ChainTransform(Transform):
    """Composition t_n(...t_1(x)) (ref ChainTransform)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._type = Type.BIJECTION if all(
            t.type == Type.BIJECTION for t in self.transforms) else Type.OTHER

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class IndependentTransform(Transform):
    """Treat `reinterpreted_batch_rank` trailing dims as event dims: the
    log-det sums over them (ref IndependentTransform)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._type = base.type

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x)
        return jnp.sum(ldj, axis=tuple(range(ldj.ndim - self.rank, ldj.ndim)))


class StackTransform(Transform):
    """Apply the i-th transform to the i-th slice along `axis`
    (ref StackTransform)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, x, method):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, method)(p.squeeze(self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map(x, "_forward")

    def _inverse(self, y):
        return self._map(y, "_inverse")

    def _forward_log_det_jacobian(self, x):
        return self._map(x, "_forward_log_det_jacobian")


class StickBreakingTransform(Transform):
    """Unconstrained R^{K} -> interior of the K+1 simplex via stick
    breaking (ref StickBreakingTransform)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        K = x.shape[-1]
        offset = jnp.log(jnp.arange(K, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        ones = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        cum = jnp.cumprod(1 - z, axis=-1)
        head = z * jnp.concatenate([ones, cum[..., :-1]], axis=-1)
        return jnp.concatenate([head, cum[..., -1:]], axis=-1)

    def _inverse(self, y):
        K = y.shape[-1] - 1
        cum = 1.0 - jnp.cumsum(y[..., :-1], axis=-1)
        shifted = jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), y.dtype), cum[..., :-1]], axis=-1)
        z = y[..., :-1] / shifted
        offset = jnp.log(jnp.arange(K, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        # dy_k/dx_k = z_k (1 - z_k) * prod_{j<k}(1 - z_j)
        K = x.shape[-1]
        offset = jnp.log(jnp.arange(K, 0, -1, dtype=x.dtype))
        t = x - offset
        z = jax.nn.sigmoid(t)
        ones = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        cum = jnp.cumprod(1 - z, axis=-1)
        shifted = jnp.concatenate([ones, cum[..., :-1]], axis=-1)
        return jnp.sum(jax.nn.log_sigmoid(t) + jax.nn.log_sigmoid(-t) +
                       jnp.log(shifted), axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)
