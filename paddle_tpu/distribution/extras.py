"""Distribution long tail (ref: python/paddle/distribution/{beta,cauchy,
dirichlet,exponential_family,multinomial,independent,transformed_distribution,
laplace,lognormal,gumbel,geometric,kl}.py) — all sampling via jax.random on
the framework's seeded key stream."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.random import next_key
from ..tensor_impl import Tensor, as_tensor_data, wrap
from . import Distribution, Normal, _arr

__all__ = [
    "Beta", "Cauchy", "Dirichlet", "ExponentialFamily", "Multinomial",
    "Independent", "TransformedDistribution", "Laplace", "LogNormal",
    "Gumbel", "Geometric", "register_kl",
]

_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a KL(p||q) implementation (ref: kl.py)."""

    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def dispatch_kl(p, q):
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn(p, q)
    return None


class ExponentialFamily(Distribution):
    """Base with Bregman-divergence entropy via the log-normalizer
    (ref: exponential_family.py). Subclasses define _natural_parameters and
    _log_normalizer; entropy falls out of autodiff of the normalizer."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural):
        raise NotImplementedError

    def entropy(self):
        nat = [jnp.asarray(n) for n in self._natural_parameters]
        lg, grads = jax.value_and_grad(
            lambda ns: jnp.sum(self._log_normalizer(*ns)))(tuple(nat))
        ent = lg
        for n, g in zip(nat, grads):
            ent = ent - jnp.sum(n * g)
        if self._mean_carrier_measure:
            ent = ent - self._mean_carrier_measure
        return wrap(ent)

    _mean_carrier_measure = 0.0


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return wrap(self.alpha * self.beta / (s * s * (s + 1)))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        k1, k2 = jax.random.split(next_key())
        ga = jax.random.gamma(k1, jnp.broadcast_to(self.alpha, shape))
        gb = jax.random.gamma(k2, jnp.broadcast_to(self.beta, shape))
        return wrap(ga / (ga + gb))

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        lbeta = (jax.scipy.special.gammaln(self.alpha)
                 + jax.scipy.special.gammaln(self.beta)
                 - jax.scipy.special.gammaln(self.alpha + self.beta))
        return wrap((self.alpha - 1) * jnp.log(v)
                    + (self.beta - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return wrap(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return wrap(self.concentration
                    / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return wrap(m * (1 - m) / (a0 + 1))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        out = jax.random.dirichlet(next_key(), self.concentration, shape)
        return wrap(out)

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        a = self.concentration
        return wrap(jnp.sum((a - 1) * jnp.log(v), -1)
                    + jax.scipy.special.gammaln(jnp.sum(a, -1))
                    - jnp.sum(jax.scipy.special.gammaln(a), -1))

    def entropy(self):
        a = self.concentration
        k = a.shape[-1]
        a0 = jnp.sum(a, -1)
        dg = jax.scipy.special.digamma
        lnB = jnp.sum(jax.scipy.special.gammaln(a), -1) \
            - jax.scipy.special.gammaln(a0)
        return wrap(lnB + (a0 - k) * dg(a0) - jnp.sum((a - 1) * dg(a), -1))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return wrap(self.loc + self.scale
                    * jax.random.cauchy(next_key(), shape))

    rsample = sample

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        z = (v - self.loc) / self.scale
        return wrap(-jnp.log(math.pi) - jnp.log(self.scale) - jnp.log1p(z * z))

    def cdf(self, value):
        v = jnp.asarray(as_tensor_data(value))
        return wrap(jnp.arctan((v - self.loc) / self.scale) / math.pi + 0.5)

    def entropy(self):
        return wrap(jnp.log(4 * math.pi * self.scale)
                    + jnp.zeros(self.batch_shape))

    def kl_divergence(self, other):
        # closed form (Chen et al. 2019)
        s0, s1 = self.scale, other.scale
        num = (s0 + s1) ** 2 + (self.loc - other.loc) ** 2
        return wrap(jnp.log(num / (4 * s0 * s1)))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_raw = _arr(probs)
        self.probs_n = self.probs_raw / jnp.sum(self.probs_raw, -1, keepdims=True)
        super().__init__(self.probs_n.shape[:-1], self.probs_n.shape[-1:])

    @property
    def mean(self):
        return wrap(self.total_count * self.probs_n)

    @property
    def variance(self):
        return wrap(self.total_count * self.probs_n * (1 - self.probs_n))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        logits = jnp.log(self.probs_n)
        draws = jax.random.categorical(
            next_key(), logits, axis=-1,
            shape=(self.total_count,) + shape)             # [N, ...]
        k = self.probs_n.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        return wrap(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        logits = jnp.log(self.probs_n)
        gl = jax.scipy.special.gammaln
        return wrap(gl(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(gl(v + 1.0), -1) + jnp.sum(v * logits, -1))

    def entropy(self):
        # exact entropy has no closed form; use the common bound-free sum over
        # the categorical part plus count term (matches reference behavior)
        p = self.probs_n
        cat_ent = -jnp.sum(p * jnp.log(p), -1)
        return wrap(self.total_count * cat_ent)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return wrap(jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return wrap(jnp.broadcast_to((2 ** 0.5) * self.scale, self.batch_shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return wrap(self.loc + self.scale * jax.random.laplace(next_key(), shape))

    rsample = sample

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        return wrap(-jnp.log(2 * self.scale) - jnp.abs(v - self.loc) / self.scale)

    def cdf(self, value):
        v = jnp.asarray(as_tensor_data(value))
        z = (v - self.loc) / self.scale
        return wrap(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, q):
        qv = jnp.asarray(as_tensor_data(q))
        a = qv - 0.5
        return wrap(self.loc - self.scale * jnp.sign(a) * jnp.log1p(-2 * jnp.abs(a)))

    def entropy(self):
        return wrap(1 + jnp.log(2 * self.scale) + jnp.zeros(self.batch_shape))

    def kl_divergence(self, other):
        d = jnp.abs(self.loc - other.loc)
        r = self.scale / other.scale
        return wrap(jnp.log(other.scale / self.scale) + r
                    * jnp.exp(-d / self.scale) + d / other.scale - 1)


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return wrap(self.loc + self.scale * 0.5772156649015329)

    @property
    def variance(self):
        return wrap((math.pi ** 2 / 6) * self.scale ** 2
                    + jnp.zeros(self.batch_shape))

    @property
    def stddev(self):
        return wrap(jnp.sqrt(as_tensor_data(self.variance)))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return wrap(self.loc + self.scale * jax.random.gumbel(next_key(), shape))

    rsample = sample

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        z = (v - self.loc) / self.scale
        return wrap(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def cdf(self, value):
        v = jnp.asarray(as_tensor_data(value))
        return wrap(jnp.exp(-jnp.exp(-(v - self.loc) / self.scale)))

    def entropy(self):
        return wrap(jnp.log(self.scale) + 1 + 0.5772156649015329
                    + jnp.zeros(self.batch_shape))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs):
        self.probs_ = _arr(probs)
        super().__init__(self.probs_.shape)

    @property
    def mean(self):
        return wrap((1 - self.probs_) / self.probs_)

    @property
    def variance(self):
        return wrap((1 - self.probs_) / self.probs_ ** 2)

    @property
    def stddev(self):
        return wrap(jnp.sqrt((1 - self.probs_)) / self.probs_)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape, minval=1e-7, maxval=1.0)
        return wrap(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        return wrap(v * jnp.log1p(-self.probs_) + jnp.log(self.probs_))

    def cdf(self, value):
        v = jnp.asarray(as_tensor_data(value))
        return wrap(1 - jnp.power(1 - self.probs_, jnp.floor(v) + 1))

    def entropy(self):
        p = self.probs_
        return wrap(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)

    def kl_divergence(self, other):
        p, q = self.probs_, other.probs_
        return wrap(jnp.log(p / q) + (1 - p) / p * jnp.log((1 - p) / (1 - q)))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        self._base = Normal(loc, scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return wrap(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return wrap(jnp.expm1(s2) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        return wrap(jnp.exp(as_tensor_data(self._base.sample(shape))))

    rsample = sample

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        base_lp = as_tensor_data(self._base.log_prob(wrap(jnp.log(v))))
        return wrap(base_lp - jnp.log(v))

    def entropy(self):
        return wrap(as_tensor_data(self._base.entropy()) + self.loc)

    def kl_divergence(self, other):
        return self._base.kl_divergence(other._base
                                        if isinstance(other, LogNormal) else other)


class Independent(Distribution):
    """Reinterprets batch dims of a base distribution as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    rsample = sample

    def log_prob(self, value):
        lp = jnp.asarray(as_tensor_data(self.base.log_prob(value)))
        return wrap(jnp.sum(lp, axis=tuple(range(lp.ndim - self.rank, lp.ndim))))

    def entropy(self):
        e = jnp.asarray(as_tensor_data(self.base.entropy()))
        return wrap(jnp.sum(e, axis=tuple(range(e.ndim - self.rank, e.ndim))))


class TransformedDistribution(Distribution):
    """Pushforward of a base distribution through a chain of transforms
    (objects with forward / inverse / forward_log_det_jacobian)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = as_tensor_data(self.base.sample(shape))
        for t in self.transforms:
            x = as_tensor_data(t.forward(wrap(x)))
        return wrap(x)

    rsample = sample

    def log_prob(self, value):
        v = jnp.asarray(as_tensor_data(value))
        ldj = jnp.zeros(())
        x = v
        for t in reversed(self.transforms):
            xin = as_tensor_data(t.inverse(wrap(x)))
            ldj = ldj + jnp.asarray(
                as_tensor_data(t.forward_log_det_jacobian(wrap(xin))))
            x = xin
        return wrap(jnp.asarray(as_tensor_data(self.base.log_prob(wrap(x)))) - ldj)
