"""Eager op dispatch.

Every eager paddle_tpu op funnels through `apply(fn, *tensor_inputs, **static_kw)`:
  * unwraps Tensors to jax arrays,
  * applies the AMP dtype policy (ref: python/paddle/amp/auto_cast.py op lists),
  * executes on device via XLA; when any input requires grad, runs through
    `jax.vjp` so the pullback (with residuals) is recorded on a tape GradNode.

This replaces the reference's C++ dygraph dispatch + PHI kernel selection
(ref: paddle/fluid/eager/auto_code_generated api, paddle/phi/kernels): XLA is
the kernel library, the tape is Python-side.

Rules for op implementations: tensor-valued arguments are passed positionally
(jax types only), all static configuration via keyword closure args.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .framework import state as _st
from .tensor_impl import Tensor, as_tensor_data
from .autograd.node import GradNode

# ---------------------------------------------------------------------------
# AMP op lists (ref: python/paddle/amp/amp_lists.py). White -> compute in
# amp dtype (bf16/fp16, feeds the MXU); black -> force fp32 (numerics).
WHITE_OPS = {
    "matmul", "bmm", "mm", "mv", "addmm", "linear", "einsum",
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "attention", "flash_attention",
}
BLACK_OPS = {
    "softmax_with_cross_entropy", "cross_entropy", "log_softmax",
    "exp", "log", "log2", "log10", "log1p", "expm1", "pow", "square",
    "mean", "sum", "prod", "cumsum", "norm", "softmax",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "rms_norm",
    "sigmoid_cross_entropy_with_logits", "cosine_similarity", "erf",
    "reduce_mean", "reduce_sum", "var", "std", "logsumexp",
}

_FLOATS = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def _amp_cast(op_name, arrays):
    level = _st._state.amp_level
    if level is None or op_name is None:
        return arrays
    amp_dtype = _st._state.amp_dtype
    white = (op_name in WHITE_OPS or op_name in _st._state.amp_custom_white)
    black = (op_name in BLACK_OPS or op_name in _st._state.amp_custom_black)
    if black:
        target = jnp.float32
    elif white or level == "O2":
        target = amp_dtype
    else:
        return arrays

    def cast(a):
        if isinstance(a, (jax.Array,)) or hasattr(a, "dtype"):
            if a.dtype in _FLOATS and a.dtype != jnp.dtype(target):
                return a.astype(target) if hasattr(a, "astype") else jnp.asarray(a, target)
        return a

    return [cast(a) for a in arrays]


def apply(fn, *inputs, op_name=None, **static_kw):
    """Dispatch `fn(*arrays, **static_kw)` eagerly with tape recording."""
    arrays = [as_tensor_data(x) for x in inputs]
    arrays = _amp_cast(op_name, arrays)

    needs_grad = _st.grad_enabled() and any(
        isinstance(x, Tensor) and not x.stop_gradient for x in inputs
    )
    if static_kw:
        call = functools.partial(fn, **static_kw)
    else:
        call = fn

    if not needs_grad:
        out = call(*arrays)
        return _wrap_outputs(out, node=None, op_name=op_name)

    out, vjp_fn = jax.vjp(call, *arrays)
    parents = [x if isinstance(x, Tensor) else None for x in inputs]
    leaves, treedef = jax.tree_util.tree_flatten(out)
    avals = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    # saved_tensors_hooks: pack the retained primals at record time; the
    # node unpacks them lazily in backward (autograd.saved_tensors_hooks)
    hooks = getattr(_st._state, "saved_tensor_hooks", None)
    primals_store = arrays
    if hooks is not None:
        pack, unpack = hooks
        primals_store = [pack(a) for a in arrays]
    node = GradNode(vjp_fn, parents, treedef, avals, op_name=op_name,
                    fwd_fn=call, primals=primals_store)
    if hooks is not None:
        node.saved_unpack = hooks[1]
    return _wrap_outputs(out, node=node, op_name=op_name)


def _wrap_outputs(out, node, op_name=None):
    leaves, treedef = jax.tree_util.tree_flatten(out)
    # amp.debugging: tensor checker / op-stats hook (eager values only —
    # tracers are checked by the compiled-path NanGuard instead)
    if (getattr(_st._state, "amp_tensor_checker", None) is not None or
            getattr(_st._state, "amp_op_stats", None) is not None):
        if not any(isinstance(l, jax.core.Tracer) for l in leaves):
            from .amp.debugging import _checker_hook
            _checker_hook(op_name, leaves)
    tensors = []
    for i, leaf in enumerate(leaves):
        differentiable = jnp.issubdtype(leaf.dtype, jnp.floating) or jnp.issubdtype(
            leaf.dtype, jnp.complexfloating)
        t = Tensor(leaf, stop_gradient=not (node is not None and differentiable))
        if node is not None and differentiable:
            t._node = node
            t._out_idx = i
        tensors.append(t)
    return jax.tree_util.tree_unflatten(treedef, tensors)


def apply_inplace(target: Tensor, fn, *inputs, op_name=None, **static_kw):
    """Run `fn` like `apply` but rebind the result onto `target` (in-place API).

    The tape must reference the *pre-mutation* value of `target`, so any input
    aliasing `target` is replaced by a snapshot (otherwise the rebound node
    would become its own parent)."""
    snap = None
    if any(x is target for x in inputs):
        snap = Tensor(target._data, stop_gradient=target.stop_gradient)
        snap._node = target._node
        snap._out_idx = target._out_idx
        inputs = tuple(snap if x is target else x for x in inputs)
    result = apply(fn, *inputs, op_name=op_name, **static_kw)
    assert isinstance(result, Tensor)
    target._data = result._data
    target._node = result._node
    target._out_idx = result._out_idx
    if result._node is not None:
        target.stop_gradient = False
    return target


def no_tape_call(fn, *inputs, **static_kw):
    """Execute without tape regardless of grad mode (utility for inference paths)."""
    arrays = [as_tensor_data(x) for x in inputs]
    return _wrap_outputs(fn(*arrays, **static_kw), node=None)
