"""Eager op dispatch.

Every eager paddle_tpu op funnels through `apply(fn, *tensor_inputs, **static_kw)`:
  * unwraps Tensors to jax arrays,
  * applies the AMP dtype policy (ref: python/paddle/amp/auto_cast.py op lists),
  * executes on device via XLA; when any input requires grad, runs through
    `jax.vjp` so the pullback (with residuals) is recorded on a tape GradNode.

This replaces the reference's C++ dygraph dispatch + PHI kernel selection
(ref: paddle/fluid/eager/auto_code_generated api, paddle/phi/kernels): XLA is
the kernel library, the tape is Python-side.

Rules for op implementations: tensor-valued arguments are passed positionally
(jax types only), all static configuration via keyword closure args.
"""
from __future__ import annotations

import functools
import threading
import types
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from .framework import state as _st
from .tensor_impl import Tensor, as_tensor_data
from .autograd.node import GradNode
from .autograd.engine import _is_float0

# ---------------------------------------------------------------------------
# AMP op lists (ref: python/paddle/amp/amp_lists.py). White -> compute in
# amp dtype (bf16/fp16, feeds the MXU); black -> force fp32 (numerics).
WHITE_OPS = {
    "matmul", "bmm", "mm", "mv", "addmm", "linear", "einsum",
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "attention", "flash_attention",
}
BLACK_OPS = {
    "softmax_with_cross_entropy", "cross_entropy", "log_softmax",
    "exp", "log", "log2", "log10", "log1p", "expm1", "pow", "square",
    "mean", "sum", "prod", "cumsum", "norm", "softmax",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "rms_norm",
    "sigmoid_cross_entropy_with_logits", "cosine_similarity", "erf",
    "reduce_mean", "reduce_sum", "var", "std", "logsumexp",
}

_FLOATS = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def _amp_cast(op_name, arrays):
    level = _st._state.amp_level
    if level is None or op_name is None:
        return arrays
    amp_dtype = _st._state.amp_dtype
    white = (op_name in WHITE_OPS or op_name in _st._state.amp_custom_white)
    black = (op_name in BLACK_OPS or op_name in _st._state.amp_custom_black)
    if black:
        target = jnp.float32
    elif white or level == "O2":
        target = amp_dtype
    else:
        return arrays

    def cast(a):
        if isinstance(a, (jax.Array,)) or hasattr(a, "dtype"):
            if a.dtype in _FLOATS and a.dtype != jnp.dtype(target):
                return a.astype(target) if hasattr(a, "astype") else jnp.asarray(a, target)
        return a

    return [cast(a) for a in arrays]


# ---------------------------------------------------------------------------
# Jit-cached dispatch.
#
# Eagerly re-tracing every op on every call (and re-deriving every pullback
# via jax.vjp) leaves the dygraph path bound by Python/trace overhead. The
# cache routes both the no-grad and vjp paths through jit-wrapped callables
# held in an LRU, so repeat dispatches of the same op signature execute a
# compiled XLA program directly.
#
# Two-level key:
#   * the LRU key identifies the *computation*: the op callable (code object
#     + hashable closure/default values + static_kw) and the ambient AMP
#     policy. Per-call lambdas created at the same source location share a
#     code object, so they hit the same entry.
#   * jax.jit's own signature cache handles input avals + shardings below
#     that, compiling one executable per (shape, dtype, sharding) signature.
#
# Closure cells holding bare jax/numpy arrays (dropout keys, lerp weights...)
# are LIFTED into traced arguments: the entry rebuilds the function with the
# per-call cell values via types.FunctionType, so a fresh PRNG key per call
# stays a fresh key instead of being baked into the trace. Anything else
# unhashable in the closure/static_kw makes the op fall back to uncached
# eager dispatch (correctness first — e.g. double-backward closures that
# capture primal lists).

_CACHE_LOCK = threading.Lock()
_JIT_CACHE: OrderedDict = OrderedDict()   # key -> _Entry
_JIT_CACHE_MAXSIZE = 1024
# keys that failed under trace -> the callable they named (pinned so the
# id()-bearing key can never alias a later, unrelated allocation)
_UNCACHEABLE_KEYS = {}
# Per call-SITE entry/hit counts: a site whose closure config varies every
# call (an annealed gumbel temperature, a loop-index shift) would compile a
# fresh executable per dispatch — worse than no cache. Sites that keep
# creating entries that never see a repeat get demoted to eager dispatch.
_SITE_STATS = {}        # site token -> [entries_created, hits]
_SITE_BLACKLIST = set()
_SITE_DEMOTE_ENTRIES = 32


class CacheStats:
    """Dispatch-cache counters (read via paddle_tpu.profiler)."""
    __slots__ = ("dispatches", "cached_calls", "hits", "misses", "traces",
                 "fallbacks", "bwd_calls", "bwd_traces")

    def __init__(self):
        self.reset()

    def reset(self):
        self.dispatches = 0     # total apply() calls
        self.cached_calls = 0   # dispatches served by a cache entry
        self.hits = 0           # LRU lookups that found an entry
        self.misses = 0         # LRU lookups that built a new entry
        self.traces = 0         # times jax actually (re)traced an entry
        self.fallbacks = 0      # dispatches that fell back to uncached eager
        self.bwd_calls = 0      # pullbacks run through the jitted backward
        self.bwd_traces = 0     # backward (re)traces

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def hit_rate(self):
        """Steady-state rate: cached dispatches that re-used compiled code."""
        if not self.cached_calls:
            return 0.0
        return 1.0 - self.traces / self.cached_calls


_stats = CacheStats()


def cache_stats():
    return _stats


def reset_cache_stats():
    _stats.reset()


def cache_enabled() -> bool:
    from . import flags as _flags
    return bool(_flags._FLAGS.get("FLAGS_eager_jit_cache", True))


def clear_cache():
    """Drop every cached executable (debugging / tests)."""
    with _CACHE_LOCK:
        _JIT_CACHE.clear()
        _UNCACHEABLE_KEYS.clear()
        _SITE_STATS.clear()
        _SITE_BLACKLIST.clear()


def cache_size():
    return len(_JIT_CACHE)


class _Unkeyable(Exception):
    pass


_PURE_CALLABLE_TYPES = tuple(t for t in (
    getattr(jax, "custom_jvp", None),
    getattr(jax, "custom_vjp", None),
    getattr(jnp, "ufunc", None),
    np.ufunc,
    types.BuiltinFunctionType,
    type(jax.jit(lambda x: x)),  # PjitFunction: jnp's pre-jitted ufuncs
) if isinstance(t, type))

_NEXT_KEY = None


def _next_key_fn():
    global _NEXT_KEY
    if _NEXT_KEY is None:
        from .framework.random import next_key
        _NEXT_KEY = next_key
    return _NEXT_KEY


_ARRAY_TYPES = (jax.Array, np.ndarray)


def _hashable(v, depth=0):
    """Hashable proxy for a static value, or raise _Unkeyable."""
    if depth > 4:
        raise _Unkeyable
    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return v
    if isinstance(v, _ARRAY_TYPES):
        raise _Unkeyable
    if isinstance(v, (list, tuple)):
        return (type(v).__name__,) + tuple(_hashable(u, depth + 1) for u in v)
    if isinstance(v, dict):
        return ("d",) + tuple(sorted(
            (k, _hashable(u, depth + 1)) for k, u in v.items()))
    if isinstance(v, slice):
        return ("slice", v.start, v.stop, v.step)
    if callable(v):
        return _callable_key(v, depth + 1)
    try:
        hash(v)
    except TypeError:
        raise _Unkeyable from None
    return v


def _callable_key(fn, depth=0):
    """Key identifying a callable's computation. Cache entries retain the
    first fn seen for a key, so id()-based components stay valid while the
    entry lives."""
    if depth > 4:
        raise _Unkeyable
    if isinstance(fn, functools.partial):
        return ("partial", _callable_key(fn.func, depth + 1),
                tuple(_hashable(a, depth + 1) for a in fn.args),
                tuple(sorted((k, _hashable(v, depth + 1))
                             for k, v in fn.keywords.items())))
    if getattr(fn, "__self__", None) is not None:
        # bound method: self may mutate without showing up in any key
        raise _Unkeyable
    code = getattr(fn, "__code__", None)
    if code is None:
        # Identity-keying is only sound for callables with no mutable state
        # the trace could bake in: jax custom-derivative wrappers, builtins,
        # ufuncs. An arbitrary callable OBJECT (e.g. a Layer read inside the
        # dispatched fn) could mutate between calls with an unchanged id —
        # refuse, so those ops stay on uncached eager dispatch.
        if isinstance(fn, _PURE_CALLABLE_TYPES):
            return ("id", id(fn))
        raise _Unkeyable
    cells = getattr(fn, "__closure__", None) or ()
    cell_key = []
    for i, c in enumerate(cells):
        try:
            val = c.cell_contents
        except ValueError:  # empty cell
            cell_key.append(("empty",))
            continue
        if val is _next_key_fn():
            # fn draws PRNG keys INSIDE its body: caching would bake the
            # trace-time key and freeze the op's randomness — never cache
            raise _Unkeyable
        if isinstance(val, jax.Array):
            if depth:
                # only the TOP-LEVEL fn's cells are lifted to traced args
                # (_Entry.dyn_values); an array one closure level down
                # would be keyed position-only yet baked as a constant
                raise _Unkeyable
            # lifted to a traced argument (see _Entry); key only the slot
            cell_key.append(("dyn", i))
        else:
            cell_key.append(_hashable(val, depth + 1))
    defaults = tuple(_hashable(d, depth + 1)
                     for d in (fn.__defaults__ or ()))
    kwdefaults = tuple(sorted(
        (k, _hashable(v, depth + 1))
        for k, v in (fn.__kwdefaults__ or {}).items()))
    return ("c", id(code), tuple(cell_key), defaults, kwdefaults)


def _dyn_cell_positions(fn):
    """Closure cell indices whose contents are jax arrays (lifted inputs)."""
    out = []
    for i, c in enumerate(getattr(fn, "__closure__", None) or ()):
        try:
            if isinstance(c.cell_contents, jax.Array):
                out.append(i)
        except ValueError:
            pass
    return out


def _rebind(fn, dyn_ix, dyn_vals):
    """fn with closure cells at dyn_ix replaced by dyn_vals (traced)."""
    if not dyn_ix:
        return fn
    cells = list(fn.__closure__)
    for pos, val in zip(dyn_ix, dyn_vals):
        cells[pos] = types.CellType(val)
    f2 = types.FunctionType(fn.__code__, fn.__globals__, fn.__name__,
                            fn.__defaults__, tuple(cells))
    f2.__kwdefaults__ = fn.__kwdefaults__
    return f2


class _Entry:
    """One cached op signature: jitted forward and jitted vjp-forward."""
    __slots__ = ("fn", "static_kw", "dyn_ix", "fwd", "vjp")

    def __init__(self, fn, static_kw):
        self.fn = fn                      # retains id()-keyed objects
        self.static_kw = static_kw
        self.dyn_ix = _dyn_cell_positions(fn)

        def run(dyn_vals, arrays):
            _stats.traces += 1
            f = _rebind(self.fn, self.dyn_ix, dyn_vals)
            call = functools.partial(f, **self.static_kw) \
                if self.static_kw else f
            return call(*arrays)

        # self.fwd / self.vjp are created once per entry; jax.jit caches one
        # executable per input signature underneath them.
        self.fwd = jax.jit(run)
        self.vjp = jax.jit(lambda dyn_vals, arrays: jax.vjp(
            lambda *a: run(dyn_vals, a), *arrays))

    def dyn_values(self, fn):
        """Current values of the lifted closure cells from the *caller's* fn
        (same code/site as self.fn, possibly a different instance)."""
        if not self.dyn_ix:
            return []
        cells = getattr(fn, "__closure__", None) or ()
        return [cells[i].cell_contents for i in self.dyn_ix]


def _site_of(callable_key):
    """Collapse a callable key to its call-SITE token (the code object /
    function identity, ignoring closure/default values)."""
    tag = callable_key[0]
    if tag == "partial":
        return _site_of(callable_key[1])
    return callable_key[:2]  # ("c", id(code)) or ("id", id(fn))


def _lookup_entry(fn, static_kw):
    """(entry, key) for this dispatch, or (None, None) when uncacheable."""
    try:
        kw_key = tuple(sorted(
            (k, _hashable(v)) for k, v in static_kw.items())) \
            if static_kw else ()
        ckey = _callable_key(fn)
        key = (ckey, kw_key,
               _st._state.amp_level, str(_st._state.amp_dtype))
    except (_Unkeyable, TypeError):
        # TypeError: sorted() over mixed-type dict keys, or an exotic
        # __hash__ raising — either way the op is simply uncacheable
        return None, None
    site = _site_of(ckey)
    with _CACHE_LOCK:
        if key in _UNCACHEABLE_KEYS or site in _SITE_BLACKLIST:
            return None, None
        entry = _JIT_CACHE.get(key)
        if entry is not None:
            _JIT_CACHE.move_to_end(key)
            _stats.hits += 1
            _SITE_STATS.setdefault(site, [0, 0])[1] += 1
            return entry, key
        # A site whose per-call config never repeats (e.g. an annealed
        # temperature in a closure) would compile per dispatch; once it has
        # created many entries without accumulating an equal number of
        # hits, demote the whole site to uncached eager dispatch.
        st = _SITE_STATS.setdefault(site, [0, 0])
        if st[0] >= _SITE_DEMOTE_ENTRIES and st[1] < st[0]:
            _SITE_BLACKLIST.add(site)
            return None, None
        st[0] += 1
        _stats.misses += 1
        from .framework.compilation_cache import ensure_persistent_cache
        ensure_persistent_cache()
        entry = _Entry(fn, dict(static_kw))
        _JIT_CACHE[key] = entry
        while len(_JIT_CACHE) > _JIT_CACHE_MAXSIZE:
            _JIT_CACHE.popitem(last=False)
    return entry, key


def _blacklist(key, fn=None):
    with _CACHE_LOCK:
        # pin the callable so the id()-bearing key can't alias a future
        # allocation after the entry (which retained fn) is dropped
        _UNCACHEABLE_KEYS[key] = fn
        _JIT_CACHE.pop(key, None)
    _stats.fallbacks += 1


def _cacheable_inputs(arrays):
    """Tracers must not cross a fresh jit boundary from a dispatch cache
    (compiled-path tracing re-enters apply via functional_trace)."""
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


# -- fused cotangent accumulation -------------------------------------------
# One compiled n-ary add per (arity, aval) signature replaces the engine's
# pairwise eager adds: k contributions to the same tape slot fuse into a
# single XLA program (and a single output buffer).

_FUSED_ACC = None


def fused_accumulate(arrays):
    global _FUSED_ACC
    if len(arrays) == 1:
        return arrays[0]
    if not cache_enabled() or not _cacheable_inputs(arrays):
        return functools.reduce(lambda a, b: a + b, arrays)
    if _FUSED_ACC is None:
        _FUSED_ACC = jax.jit(
            lambda *xs: functools.reduce(lambda a, b: a + b, xs))
    return _FUSED_ACC(*arrays)


# -- symbolic zero cotangents ------------------------------------------------
class SymbolicZero:
    """Placeholder for a missing output cotangent. Registered as a pytree
    node with NO leaves, so its (shape, dtype) ride in the treedef: the
    jitted backward materializes the zeros inside the compiled program
    (where XLA folds them) instead of allocating real buffers eagerly."""
    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = dtype

    def materialize(self):
        if self.dtype == "float0":
            return np.zeros(self.shape, jax.dtypes.float0)
        return jnp.zeros(self.shape, self.dtype)

    def __repr__(self):
        return f"SymbolicZero({self.shape}, {self.dtype})"


jax.tree_util.register_pytree_node(
    SymbolicZero,
    lambda z: ((), (z.shape, z.dtype)),
    lambda aux, _: SymbolicZero(*aux))


def symbolic_zero_for(aval):
    if jnp.issubdtype(aval.dtype, jnp.floating) or \
            jnp.issubdtype(aval.dtype, jnp.complexfloating):
        return SymbolicZero(aval.shape, jnp.dtype(aval.dtype).name)
    return SymbolicZero(aval.shape, "float0")


def _is_symzero(x):
    return isinstance(x, SymbolicZero)


def _materialize_cots(struct):
    leaves, treedef = jax.tree_util.tree_flatten(struct, is_leaf=_is_symzero)
    leaves = [l.materialize() if _is_symzero(l) else l for l in leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves)


_BWD_JIT = None


def _get_bwd_jit():
    global _BWD_JIT
    if _BWD_JIT is None:
        def bwd(vjp_fn, cot_struct):
            _stats.bwd_traces += 1
            raw = vjp_fn(_materialize_cots(cot_struct))
            # float0 (non-differentiable input) -> None: an empty pytree is a
            # legal jit output, a float0 np array is not
            return tuple(None if _is_float0(g) else g for g in raw)
        _BWD_JIT = jax.jit(bwd)
    return _BWD_JIT


def run_pullback(node, cot_struct):
    """Execute a tape node's pullback on a cotangent structure whose missing
    entries are SymbolicZero markers. Cached (jit-returned) pullbacks run
    through one shared jitted applier — the vjp_fn is a tree_util.Partial
    whose treedef is stable per signature, so the backward compiles once and
    replays; uncached pullbacks run eagerly on materialized zeros."""
    if getattr(node, "vjp_cached", False) and cache_enabled():
        leaves = jax.tree_util.tree_leaves(cot_struct, is_leaf=_is_symzero)
        if not any(isinstance(l, jax.core.Tracer) for l in leaves):
            _stats.bwd_calls += 1
            try:
                return _get_bwd_jit()(node.vjp_fn, cot_struct)
            except Exception:
                # eager path below; demote the node so later backward calls
                # (retain_graph) don't pay a failed trace attempt each time
                node.vjp_cached = False
    return node.vjp_fn(_materialize_cots(cot_struct))


def apply(fn, *inputs, op_name=None, **static_kw):
    """Dispatch `fn(*arrays, **static_kw)` eagerly with tape recording."""
    _stats.dispatches += 1
    arrays = [as_tensor_data(x) for x in inputs]
    arrays = _amp_cast(op_name, arrays)

    needs_grad = _st.grad_enabled() and any(
        isinstance(x, Tensor) and not x.stop_gradient for x in inputs
    )

    entry = key = None
    if cache_enabled() and _cacheable_inputs(arrays):
        entry, key = _lookup_entry(fn, static_kw)
        if entry is None:
            # unkeyable op (or previously blacklisted): uncached dispatch
            _stats.fallbacks += 1

    if not needs_grad:
        if entry is not None:
            try:
                out = entry.fwd(entry.dyn_values(fn), arrays)
                _stats.cached_calls += 1
                return _wrap_outputs(out, node=None, op_name=op_name)
            except Exception:
                # Re-run eagerly. Blacklist ONLY if that succeeds (a
                # jit-specific incompatibility); a genuine user error
                # re-raises below without poisoning the key.
                call = functools.partial(fn, **static_kw) if static_kw else fn
                out = call(*arrays)
                _blacklist(key, fn)
                return _wrap_outputs(out, node=None, op_name=op_name)
        call = functools.partial(fn, **static_kw) if static_kw else fn
        out = call(*arrays)
        return _wrap_outputs(out, node=None, op_name=op_name)

    vjp_cached = False
    out = None
    call = functools.partial(fn, **static_kw) if static_kw else fn
    if entry is not None:
        try:
            out, vjp_fn = entry.vjp(entry.dyn_values(fn), arrays)
            _stats.cached_calls += 1
            vjp_cached = True
        except Exception:
            # as above: eager first, blacklist only on eager success
            out, vjp_fn = jax.vjp(call, *arrays)
            _blacklist(key, fn)
    if out is None and not vjp_cached:
        out, vjp_fn = jax.vjp(call, *arrays)
    parents = [x if isinstance(x, Tensor) else None for x in inputs]
    leaves, treedef = jax.tree_util.tree_flatten(out)
    # arrays/tracers carry their aval (shape+dtype view) — constructing a
    # fresh ShapeDtypeStruct per leaf is pure dispatch overhead
    avals = [getattr(l, "aval", None) or jax.ShapeDtypeStruct(l.shape, l.dtype)
             for l in leaves]
    # saved_tensors_hooks: pack the retained primals at record time; the
    # node unpacks them lazily in backward (autograd.saved_tensors_hooks)
    hooks = getattr(_st._state, "saved_tensor_hooks", None)
    primals_store = arrays
    if hooks is not None:
        pack, unpack = hooks
        primals_store = [pack(a) for a in arrays]
    node = GradNode(vjp_fn, parents, treedef, avals, op_name=op_name,
                    fwd_fn=call, primals=primals_store)
    node.vjp_cached = vjp_cached
    if hooks is not None:
        node.saved_unpack = hooks[1]
    return _wrap_outputs(out, node=node, op_name=op_name)


def _wrap_outputs(out, node, op_name=None):
    leaves, treedef = jax.tree_util.tree_flatten(out)
    # amp.debugging: tensor checker / op-stats hook (eager values only —
    # tracers are checked by the compiled-path NanGuard instead)
    if (getattr(_st._state, "amp_tensor_checker", None) is not None or
            getattr(_st._state, "amp_op_stats", None) is not None):
        if not any(isinstance(l, jax.core.Tracer) for l in leaves):
            from .amp.debugging import _checker_hook
            _checker_hook(op_name, leaves)
    tensors = []
    for i, leaf in enumerate(leaves):
        differentiable = jnp.issubdtype(leaf.dtype, jnp.floating) or jnp.issubdtype(
            leaf.dtype, jnp.complexfloating)
        t = Tensor(leaf, stop_gradient=not (node is not None and differentiable))
        if node is not None and differentiable:
            t._node = node
            t._out_idx = i
        tensors.append(t)
    return jax.tree_util.tree_unflatten(treedef, tensors)


def apply_inplace(target: Tensor, fn, *inputs, op_name=None, **static_kw):
    """Run `fn` like `apply` but rebind the result onto `target` (in-place API).

    The tape must reference the *pre-mutation* value of `target`, so any input
    aliasing `target` is replaced by a snapshot (otherwise the rebound node
    would become its own parent)."""
    snap = None
    if any(x is target for x in inputs):
        snap = Tensor(target._data, stop_gradient=target.stop_gradient)
        snap._node = target._node
        snap._out_idx = target._out_idx
        inputs = tuple(snap if x is target else x for x in inputs)
    result = apply(fn, *inputs, op_name=op_name, **static_kw)
    assert isinstance(result, Tensor)
    target._data = result._data
    target._node = result._node
    target._out_idx = result._out_idx
    if result._node is not None:
        target.stop_gradient = False
    return target


def no_tape_call(fn, *inputs, **static_kw):
    """Execute without tape regardless of grad mode (utility for inference paths)."""
    arrays = [as_tensor_data(x) for x in inputs]
    return _wrap_outputs(fn(*arrays, **static_kw), node=None)
