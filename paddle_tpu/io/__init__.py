"""paddle_tpu.io — datasets & loading (ref: python/paddle/io/*).

DataLoader defaults to a thread-pool prefetch pipeline (host-side batch
assembly overlapped with device steps): on TPU the loader's job is to keep
host->HBM transfers ahead of the step loop, and threads + jnp.asarray
achieve that without pickling overhead. For transform-heavy *python*
pipelines (GIL-bound vision preprocessing) `worker_mode="process"` forks
real worker processes like the reference's multiprocess loader
(ref: io/dataloader/dataloader_iter.py:439) — see process_workers.py.
"""
from __future__ import annotations

import itertools
import queue
import threading

import numpy as np

from ..tensor_impl import Tensor
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .native import TokenStream  # noqa: F401  (C++-backed corpus stream)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler, SubsetRandomSampler,
)


def default_collate_fn(batch):
    """Stack samples into batch Tensors (ref: io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 worker_mode="thread"):
        if worker_mode not in ("thread", "process"):
            raise ValueError("worker_mode must be 'thread' or 'process'")
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        from ..incubate.autotune import dataloader_num_workers
        self.num_workers = dataloader_num_workers(num_workers)
        self.worker_mode = worker_mode
        self.worker_init_fn = worker_init_fn
        self._user_collate = collate_fn
        # honored: a stuck worker (deadlocked transform, dead NFS mount)
        # raises after `timeout` seconds instead of hanging the step loop
        # forever. 0 keeps the reference default of waiting indefinitely.
        self.timeout = float(timeout or 0)
        if self.timeout < 0:
            raise ValueError(f"timeout must be >= 0, got {timeout!r}")
        # exact-resume support: batches served this epoch / skip request
        self._served = 0
        self._resume_skip = 0
        if not isinstance(prefetch_factor, int) or prefetch_factor < 1:
            raise ValueError(
                f"prefetch_factor must be a positive int, got "
                f"{prefetch_factor!r}")
        # honored as given: prefetch_factor=1 keeps at most one assembled
        # batch per worker in flight (memory-constrained hosts disable
        # deeper prefetch this way; the seed silently raised it to 2)
        self.prefetch_factor = prefetch_factor
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, batch_size=batch_size, shuffle=shuffle,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    # -- resumable position (exact mid-epoch resume) -----------------------
    def state_dict(self):
        """Position within the current epoch: how many batches this loader
        has yielded. Checkpoint it next to the model/optimizer state; on
        restore, ``load_state_dict`` makes the NEXT ``__iter__`` skip that
        many batches — for the map-style/batch_sampler path the skip
        consumes only sampler indices (no data is fetched), so resuming
        deep into an epoch is cheap."""
        return {"batches_served": self._served}

    def load_state_dict(self, state):
        self._resume_skip = int(state.get("batches_served", 0))

    def _iter_batches(self, skip=0):
        if self._iterable:
            it = iter(self.dataset)
            # iterable datasets have no index stream to skip over: resume
            # consumes (and drops) the already-served batches
            for _ in range(skip + 1):
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk or (len(chunk) < self.batch_size
                                 and self.drop_last):
                    return
            while chunk:
                yield self.collate_fn(chunk)
                chunk = list(itertools.islice(it, self.batch_size))
                if len(chunk) < self.batch_size and self.drop_last:
                    return
        elif self.batch_sampler is None:
            for i in range(skip, len(self.dataset)):  # batch_size=None
                yield self.dataset[i]
        else:
            # skip consumes only sampler indices — no data is fetched for
            # the already-served prefix, so deep mid-epoch resume is cheap
            for indices in itertools.islice(self.batch_sampler, skip, None):
                yield self._fetch(indices)

    def __iter__(self):
        skip, self._resume_skip = self._resume_skip, 0
        self._served = skip
        if self.num_workers <= 0:
            src = self._iter_batches(skip)
        elif self.worker_mode == "process":
            src = self._iter_process(skip)
        else:  # threaded prefetch: producer assembles batches ahead
            src = self._iter_threads(skip)
        for b in src:
            self._served += 1
            yield b


    def _iter_process(self, skip=0):
        """Multiprocess fetch (ref: dataloader_iter.py:439): workers collate
        at the numpy level; the parent re-wraps leaves as Tensors."""
        from .process_workers import ProcessPool, np_collate
        if self._iterable or self.batch_sampler is None:
            import warnings
            warnings.warn(
                "worker_mode='process' supports map-style batched datasets; "
                "falling back to threads for this dataset")
            yield from self._iter_threads(skip)
            return
        # the explicit-default case routes to the numpy collate: Tensor
        # construction must not happen in a forked child (device handles
        # are not fork-safe); user collates get their output forced to
        # numpy in the worker and re-wrapped here
        user = self._user_collate
        if user is default_collate_fn:
            user = None
        worker_collate = user or np_collate
        pool = ProcessPool(self.dataset, worker_collate, self.num_workers,
                           prefetch_factor=self.prefetch_factor,
                           worker_init_fn=self.worker_init_fn,
                           timeout=self.timeout)
        try:
            batches = itertools.islice(self.batch_sampler, skip, None)
            for batch in pool.run(batches):
                yield _wrap_np(batch)
        finally:
            pool.shutdown()

    def _iter_threads(self, skip=0):
        q: queue.Queue = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()
        err = []

        def producer():
            try:
                for b in self._iter_batches(skip):
                    q.put(b)
            except Exception as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            try:
                item = q.get(timeout=self.timeout or None)
            except queue.Empty:
                # the producer thread is wedged (deadlocked __getitem__ /
                # transform, hung filesystem): fail loudly instead of
                # blocking the step loop forever
                raise RuntimeError(
                    f"DataLoader worker produced no batch within "
                    f"timeout={self.timeout}s — stuck dataset/transform "
                    f"code (worker thread alive: {t.is_alive()})")
            if item is sentinel:
                if err:
                    raise err[0]
                return
            yield item


def _wrap_np(batch):
    """Wrap numpy-collated leaves as Tensors (nested structure preserved)."""
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return type(batch)(_wrap_np(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _wrap_np(v) for k, v in batch.items()}
    return batch


def get_worker_info():
    """ref: paddle.io.get_worker_info — WorkerInfo in a worker process,
    None in the main process / thread workers."""
    from .process_workers import get_worker_info as _gwi
    return _gwi()
