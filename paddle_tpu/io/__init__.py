"""paddle_tpu.io — datasets & loading (ref: python/paddle/io/*).

DataLoader uses a thread-pool prefetch pipeline (host-side batch assembly
overlapped with device steps) instead of the reference's multiprocess C++
workers: on TPU the loader's job is to keep host->HBM transfers ahead of the
step loop, and threads + jnp.asarray achieve that without pickling overhead.
"""
from __future__ import annotations

import itertools
import queue
import threading

import numpy as np

from ..tensor_impl import Tensor
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .native import TokenStream  # noqa: F401  (C++-backed corpus stream)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler, SubsetRandomSampler,
)


def default_collate_fn(batch):
    """Stack samples into batch Tensors (ref: io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        from ..incubate.autotune import dataloader_num_workers
        self.num_workers = dataloader_num_workers(num_workers)
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, batch_size=batch_size, shuffle=shuffle,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_batches(self):
        if self._iterable:
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):  # batch_size=None: no batching
                yield self.dataset[i]
        else:
            for indices in self.batch_sampler:
                yield self._fetch(indices)

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        # threaded prefetch: producer assembles batches ahead of the consumer
        q: queue.Queue = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()
        err = []

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            except Exception as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if err:
                    raise err[0]
                return
            yield item


def get_worker_info():
    return None
