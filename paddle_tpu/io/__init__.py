"""paddle_tpu.io — datasets & loading (ref: python/paddle/io/*).

DataLoader defaults to a thread-pool prefetch pipeline (host-side batch
assembly overlapped with device steps): on TPU the loader's job is to keep
host->HBM transfers ahead of the step loop, and threads + jnp.asarray
achieve that without pickling overhead. For transform-heavy *python*
pipelines (GIL-bound vision preprocessing) `worker_mode="process"` forks
real worker processes like the reference's multiprocess loader
(ref: io/dataloader/dataloader_iter.py:439) — see process_workers.py.
"""
from __future__ import annotations

import itertools
import queue
import threading

import numpy as np

from ..tensor_impl import Tensor
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .native import TokenStream  # noqa: F401  (C++-backed corpus stream)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler, SubsetRandomSampler,
)


def default_collate_fn(batch):
    """Stack samples into batch Tensors (ref: io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 worker_mode="thread"):
        if worker_mode not in ("thread", "process"):
            raise ValueError("worker_mode must be 'thread' or 'process'")
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        from ..incubate.autotune import dataloader_num_workers
        self.num_workers = dataloader_num_workers(num_workers)
        self.worker_mode = worker_mode
        self.worker_init_fn = worker_init_fn
        self._user_collate = collate_fn
        # honored: a stuck worker (deadlocked transform, dead NFS mount)
        # raises after `timeout` seconds instead of hanging the step loop
        # forever. 0 keeps the reference default of waiting indefinitely.
        self.timeout = float(timeout or 0)
        if self.timeout < 0:
            raise ValueError(f"timeout must be >= 0, got {timeout!r}")
        # exact-resume support: batches served this epoch / skip request;
        # iterable datasets additionally track the EXACT sample count
        # (their short final batch is unknowable up front) + epoch end
        self._served = 0
        self._resume_skip = 0
        self._samples_exact = None
        self._epoch_end = False
        if not isinstance(prefetch_factor, int) or prefetch_factor < 1:
            raise ValueError(
                f"prefetch_factor must be a positive int, got "
                f"{prefetch_factor!r}")
        # honored as given: prefetch_factor=1 keeps at most one assembled
        # batch per worker in flight (memory-constrained hosts disable
        # deeper prefetch this way; the seed silently raised it to 2)
        self.prefetch_factor = prefetch_factor
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, batch_size=batch_size, shuffle=shuffle,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    # -- resumable position (exact mid-epoch resume) -----------------------
    def _samples_per_batch(self):
        """GLOBAL samples one yielded batch advances the epoch by, or None
        when unknowable (a custom batch_sampler without a ``batch_size``
        attribute). A DistributedBatchSampler yields this host's
        1/nranks shard, so each yield advances the global stream by
        batch_size * nranks — recording in global terms is what makes the
        position meaningful across a topology change."""
        if self._iterable:
            return int(self.batch_size) if self.batch_size else None
        if self.batch_sampler is None:
            return 1  # batch_size=None: one sample per yield
        bs = getattr(self.batch_sampler, "batch_size", None)
        if not bs:
            return None
        return int(bs) * int(getattr(self.batch_sampler, "nranks", 1) or 1)

    def _epoch_samples(self):
        """Global samples one epoch serves (the clamp bound for a short
        final batch), or None when unknowable."""
        if not self._iterable and self.batch_sampler is not None:
            total = getattr(self.batch_sampler, "total_size", None)
            if total is not None:  # distributed sampler pads to this
                return int(total)
        try:
            return len(self.dataset)
        except TypeError:
            return None

    def state_dict(self):
        """Position within the current epoch in GLOBAL-SAMPLE terms:
        ``samples_served`` (= batches x samples-per-batch, alongside the
        producing ``batch_size``) plus the raw ``batches_served``.
        Checkpoint it next to the model/optimizer state; on restore,
        ``load_state_dict`` makes the NEXT ``__iter__`` skip to that
        sample — for the map-style/batch_sampler path the skip consumes
        only sampler indices (no data is fetched), so resuming deep into
        an epoch is cheap. Recording samples rather than batches makes the
        position topology-independent: a resume whose global batch size
        differs re-derives its own batch skip (and a position that does
        not fall on the new batch boundary is REFUSED with the fields
        named, where the old index-only skip silently desynced)."""
        state = {"batches_served": self._served}
        spb = self._samples_per_batch()
        if spb and self._iterable and self._samples_exact is None \
                and self._served:
            # worker-prefetch iterable (no exact consumer-side count) with
            # no length bound: batches x batch_size could overstate past a
            # short final batch — record the batch position only (legacy
            # skip on resume) rather than an unverifiable sample count
            return state
        if spb:
            samples = self._served * spb
            # a short FINAL batch (drop_last=False) serves fewer samples
            n = self._epoch_samples()
            if n is not None:
                samples = min(samples, n)
            if self._iterable and self._samples_exact is not None:
                samples = self._samples_exact  # exact incl. short batch
            state["samples_served"] = samples
            state["batch_size"] = spb
            done = self._epoch_end or (n is not None and samples >= n)
            if not done and not self._iterable:
                # map-style completion is verifiable CONSUMER-side from
                # the batch count (len(batch_sampler) / len(dataset)) —
                # this covers a drop_last=True epoch under worker
                # prefetch, where _epoch_end stays unset (the producer
                # thread runs ahead of the user) and samples < n
                try:
                    done = self._served >= len(self)
                except TypeError:
                    pass
            if done:
                # a non-boundary position is resumable iff it is the END
                # of the epoch; mark it so the restoring loader (which may
                # not know the epoch length — iterable datasets) can tell
                state["epoch_end"] = True
        return state

    def load_state_dict(self, state):
        if "samples_served" in state:
            spb = self._samples_per_batch()
            samples = int(state["samples_served"])
            if spb:
                if samples % spb:
                    n = self._epoch_samples()
                    if state.get("epoch_end") or \
                            (n is not None and samples == n):
                        # EPOCH-END position (the final batch was short,
                        # drop_last=False): every batch was served — skip
                        # the whole epoch; the next __iter__ after the
                        # one-shot skip starts the following epoch fresh
                        self._resume_skip = -(-samples // spb)
                        return
                    raise ValueError(
                        f"DataLoader resume position is not on a batch "
                        f"boundary: checkpoint samples_served={samples} "
                        f"(batch_size={state.get('batch_size')}) does not "
                        f"divide by this loader's batch_size={spb} — the "
                        f"resuming run would silently desync mid-batch; "
                        f"restore with a batch size that divides "
                        f"{samples}")
                self._resume_skip = samples // spb
                return
            import warnings
            warnings.warn(
                f"DataLoader cannot derive its samples-per-batch (custom "
                f"batch_sampler without a batch_size attribute): falling "
                f"back to the raw batch skip of {state.get('batches_served')}"
                f" — if this loader's batching differs from the producing "
                f"run's (samples_served={samples}, batch_size="
                f"{state.get('batch_size')}), the resumed sample sequence "
                f"will desync")
        self._resume_skip = int(state.get("batches_served", 0))

    def _iter_batches(self, skip=0):
        if self._iterable:
            it = iter(self.dataset)
            track = self._samples_exact is not None
            # iterable datasets have no index stream to skip over: resume
            # consumes (and drops) the already-served batches
            for _ in range(skip + 1):
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk or (len(chunk) < self.batch_size
                                 and self.drop_last):
                    if track:
                        self._epoch_end = True
                    return
            while chunk:
                if track:
                    self._samples_exact += len(chunk)
                yield self.collate_fn(chunk)
                chunk = list(itertools.islice(it, self.batch_size))
                if len(chunk) < self.batch_size and self.drop_last:
                    if track:
                        self._epoch_end = True
                    return
            if track:
                self._epoch_end = True
        elif self.batch_sampler is None:
            for i in range(skip, len(self.dataset)):  # batch_size=None
                yield self.dataset[i]
            if self.num_workers <= 0:
                self._epoch_end = True
        else:
            # skip consumes only sampler indices — no data is fetched for
            # the already-served prefix, so deep mid-epoch resume is cheap
            for indices in itertools.islice(self.batch_sampler, skip, None):
                yield self._fetch(indices)
            # exhaustion marks epoch end even when drop_last truncated the
            # tail (samples_served < len(dataset) yet the epoch is DONE);
            # consumer-side only — worker prefetch runs ahead of the user
            if self.num_workers <= 0:
                self._epoch_end = True

    def __iter__(self):
        skip, self._resume_skip = self._resume_skip, 0
        self._served = skip
        self._epoch_end = False
        spb = self._samples_per_batch()
        # exact sample tracking only where the generator runs on the
        # consumer's thread (worker prefetch counts AHEAD of the user)
        self._samples_exact = (skip * spb if (self._iterable and spb
                                              and self.num_workers <= 0)
                               else None)
        if self.num_workers <= 0:
            src = self._iter_batches(skip)
        elif self.worker_mode == "process":
            src = self._iter_process(skip)
        else:  # threaded prefetch: producer assembles batches ahead
            src = self._iter_threads(skip)
        for b in src:
            self._served += 1
            yield b


    def _iter_process(self, skip=0):
        """Multiprocess fetch (ref: dataloader_iter.py:439): workers collate
        at the numpy level; the parent re-wraps leaves as Tensors."""
        from .process_workers import ProcessPool, np_collate
        if self._iterable or self.batch_sampler is None:
            import warnings
            warnings.warn(
                "worker_mode='process' supports map-style batched datasets; "
                "falling back to threads for this dataset")
            yield from self._iter_threads(skip)
            return
        # the explicit-default case routes to the numpy collate: Tensor
        # construction must not happen in a forked child (device handles
        # are not fork-safe); user collates get their output forced to
        # numpy in the worker and re-wrapped here
        user = self._user_collate
        if user is default_collate_fn:
            user = None
        worker_collate = user or np_collate
        pool = ProcessPool(self.dataset, worker_collate, self.num_workers,
                           prefetch_factor=self.prefetch_factor,
                           worker_init_fn=self.worker_init_fn,
                           timeout=self.timeout)
        try:
            batches = itertools.islice(self.batch_sampler, skip, None)
            for batch in pool.run(batches):
                yield _wrap_np(batch)
        finally:
            pool.shutdown()

    def _iter_threads(self, skip=0):
        q: queue.Queue = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()
        err = []

        def producer():
            try:
                for b in self._iter_batches(skip):
                    q.put(b)
            except Exception as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            try:
                item = q.get(timeout=self.timeout or None)
            except queue.Empty:
                # the producer thread is wedged (deadlocked __getitem__ /
                # transform, hung filesystem): fail loudly instead of
                # blocking the step loop forever
                raise RuntimeError(
                    f"DataLoader worker produced no batch within "
                    f"timeout={self.timeout}s — stuck dataset/transform "
                    f"code (worker thread alive: {t.is_alive()})")
            if item is sentinel:
                if err:
                    raise err[0]
                return
            yield item


def _wrap_np(batch):
    """Wrap numpy-collated leaves as Tensors (nested structure preserved)."""
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return type(batch)(_wrap_np(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _wrap_np(v) for k, v in batch.items()}
    return batch


def get_worker_info():
    """ref: paddle.io.get_worker_info — WorkerInfo in a worker process,
    None in the main process / thread workers."""
    from .process_workers import get_worker_info as _gwi
    return _gwi()
