"""Native (C++) host-side data pipeline (ref: paddle/fluid/operators/reader/*,
python/paddle/distributed/fleet/data_generator/*).

The reference keeps the GPU fed with C++ DataLoader workers; on TPU the
equivalent job is assembling token batches on the host fast enough to overlap
with jitted device steps. ``native/dataio.cpp`` provides:

  * mmap'd token-corpus reader (u16 / u32 / i64 token files)
  * a *stateless-permutation* sampler: sample order is a Feistel permutation
    of window indices keyed by (seed, epoch) — deterministic, infinitely
    streaming, and checkpointable with a single integer (the batch cursor)
  * multithreaded batch assembly with strict in-order emission

The pure-Python fallback below implements bit-identical sampling (same
splitmix64/Feistel arithmetic) so behavior is unchanged when a C++ toolchain
is unavailable; tests assert C++/Python parity.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "dataio.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libdataio.so")

_lib = None
_lib_lock = threading.Lock()
_MASK64 = (1 << 64) - 1


_build_error = None


def _compile_lib():
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"  # per-pid: concurrent ranks may race
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"g++ build of {_SRC} failed:\n{e.stderr.decode(errors='replace')}") from e
    os.replace(tmp, _LIB_PATH)


def load_library(rebuild=False):
    """Build (if needed) and load the native dataio library, or None."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None and not rebuild:
            return _lib
        if _build_error is not None and not rebuild:
            return None  # don't retry a known-broken toolchain every call
        try:
            have_lib = os.path.exists(_LIB_PATH)
            have_src = os.path.exists(_SRC)
            stale = rebuild or not have_lib or (
                have_src and os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
            )  # a prebuilt .so without the source tree is fine as-is
            if stale:
                _compile_lib()
            lib = ctypes.CDLL(_LIB_PATH)
        except (OSError, RuntimeError, FileNotFoundError) as e:
            _build_error = e
            return None
        lib.dio_corpus_open.restype = ctypes.c_void_p
        lib.dio_corpus_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dio_corpus_len.restype = ctypes.c_longlong
        lib.dio_corpus_len.argtypes = [ctypes.c_void_p]
        lib.dio_corpus_close.argtypes = [ctypes.c_void_p]
        lib.dio_stream_create.restype = ctypes.c_void_p
        lib.dio_stream_create.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_ulonglong, ctypes.c_int, ctypes.c_int,
        ]
        lib.dio_stream_nwindows.restype = ctypes.c_longlong
        lib.dio_stream_nwindows.argtypes = [ctypes.c_void_p]
        lib.dio_stream_next.restype = ctypes.c_int
        lib.dio_stream_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
        lib.dio_stream_state.restype = ctypes.c_longlong
        lib.dio_stream_state.argtypes = [ctypes.c_void_p]
        lib.dio_stream_seek.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.dio_stream_destroy.argtypes = [ctypes.c_void_p]
        lib.dio_feistel.restype = ctypes.c_longlong
        lib.dio_feistel.argtypes = [ctypes.c_longlong, ctypes.c_longlong, ctypes.c_ulonglong]
        _lib = lib
        return _lib


# ---------------------------------------------------------------------------
# Python mirror of the C++ sampling arithmetic (bit-identical).
# ---------------------------------------------------------------------------

def splitmix64(x):
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def feistel_permute(idx, n, key):
    """Stateless pseudo-random permutation of [0, n) (cycle-walking Feistel)."""
    if n <= 1:
        return 0
    bits = 0
    while (1 << bits) < n:
        bits += 1
    half = (bits + 1) // 2
    mask = (1 << half) - 1
    x = idx
    while True:
        l, r = x >> half, x & mask
        for rnd in range(4):
            f = splitmix64(r ^ splitmix64((key + rnd) & _MASK64)) & mask
            l, r = r, l ^ f
        x = (l << half) | r
        if x < n:
            return x


def _epoch_key(seed, epoch):
    return splitmix64((seed ^ splitmix64(epoch)) & _MASK64)


def sample_to_window(sample, nwindows, seed):
    epoch, in_epoch = divmod(sample, nwindows)
    return feistel_permute(in_epoch, nwindows, _epoch_key(seed, epoch))


_TOKEN_BYTES = {np.dtype(np.uint16): 2, np.dtype(np.uint32): 4, np.dtype(np.int32): 4,
                np.dtype(np.int64): 8}


class TokenStream:
    """Deterministic infinite (input, label) batch stream over a token file.

    Each sample is a non-overlapping window of ``seq_len + 1`` tokens; inputs
    are tokens [0:seq_len), labels are shifted by one. ``state_dict`` /
    ``set_state_dict`` checkpoint the cursor for exact resume, which the
    elastic restart harness builds on.
    """

    def __init__(self, path, seq_len, batch_size, seed=0, dtype=np.uint16,
                 num_threads=4, queue_depth=8, backend="auto"):
        self.path = os.fspath(path)
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self.seed = int(seed) & _MASK64
        self.dtype = np.dtype(dtype)
        if self.dtype not in _TOKEN_BYTES:
            raise ValueError(f"unsupported token dtype {dtype}")
        self._token_bytes = _TOKEN_BYTES[self.dtype]
        self._native = None
        self._mmap = None
        self._cursor = 0  # python-backend batch cursor

        lib = load_library() if backend in ("auto", "native") else None
        if backend == "native" and lib is None:
            raise RuntimeError(f"native dataio library unavailable: {_build_error}")
        if lib is not None:
            corpus = lib.dio_corpus_open(self.path.encode(), self._token_bytes)
            if not corpus:
                raise FileNotFoundError(f"cannot open token corpus {self.path}")
            stream = lib.dio_stream_create(
                corpus, self.seq_len, self.batch_size, self.seed,
                int(num_threads), int(queue_depth))
            if not stream:
                lib.dio_corpus_close(corpus)
                raise ValueError("corpus too small for seq_len")
            self._native = (lib, corpus, stream)
            self.ntokens = int(lib.dio_corpus_len(corpus))
            self.nwindows = int(lib.dio_stream_nwindows(stream))
        else:
            self._mmap = np.memmap(self.path, dtype=self.dtype, mode="r")
            self.ntokens = int(self._mmap.shape[0])
            self.nwindows = (self.ntokens - 1) // self.seq_len
            if self.nwindows <= 0:
                raise ValueError("corpus too small for seq_len")
        self.batches_per_epoch = self.nwindows // self.batch_size

    @property
    def backend(self):
        return "native" if self._native is not None else "python"

    def _next_python(self):
        row = self.seq_len + 1
        out = np.empty((self.batch_size, row), dtype=np.int32)
        base_sample = self._cursor * self.batch_size
        for j in range(self.batch_size):
            w = sample_to_window(base_sample + j, self.nwindows, self.seed)
            out[j] = self._mmap[w * self.seq_len: w * self.seq_len + row].astype(np.int32)
        self._cursor += 1
        return out

    def next(self):
        """Return (inputs, labels), each int32 [batch_size, seq_len]."""
        if self._native is not None:
            lib, _, stream = self._native
            buf = np.empty((self.batch_size, self.seq_len + 1), dtype=np.int32)
            ok = lib.dio_stream_next(stream, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if not ok:
                raise RuntimeError("native stream stopped")
        else:
            buf = self._next_python()
        return buf[:, :-1], buf[:, 1:]

    def __iter__(self):
        while True:
            yield self.next()

    def state_dict(self):
        if self._native is not None:
            lib, _, stream = self._native
            cursor = int(lib.dio_stream_state(stream))
        else:
            cursor = self._cursor
        return {"cursor": cursor, "seed": self.seed, "seq_len": self.seq_len,
                "batch_size": self.batch_size}

    def set_state_dict(self, state):
        for k in ("seed", "seq_len", "batch_size"):
            if k in state and int(state[k]) != getattr(self, k):
                raise ValueError(
                    f"stream {k}={getattr(self, k)} does not match checkpoint "
                    f"{k}={state[k]}; exact resume would replay different data")
        cursor = int(state["cursor"])
        if self._native is not None:
            lib, _, stream = self._native
            lib.dio_stream_seek(stream, cursor)
        else:
            self._cursor = cursor

    def close(self):
        if self._native is not None:
            lib, corpus, stream = self._native
            lib.dio_stream_destroy(stream)
            lib.dio_corpus_close(corpus)
            self._native = None
        self._mmap = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def write_token_file(path, tokens, dtype=np.uint16):
    """Helper: write a flat token array as a corpus file TokenStream can read."""
    np.asarray(tokens, dtype=dtype).tofile(os.fspath(path))
