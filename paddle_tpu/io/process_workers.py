"""Multiprocess DataLoader workers (ref: python/paddle/io/dataloader/
dataloader_iter.py:439 _DataLoaderIterMultiProcess + worker.py).

Thread workers (the default) keep host->HBM transfers ahead of the step
loop, but heavy *python* transforms (vision pipelines) serialize on the
GIL. Process mode forks worker processes that fetch+collate batches at the
numpy level and ship them back pickled through pipes; the parent re-wraps
leaves as Tensors and preserves batch order with a sequence buffer. Workers
must not touch jax (fork inherits the initialized backend; device handles
are not fork-safe) — which is exactly why collation stays numpy-side here.
"""
from __future__ import annotations

import multiprocessing
import traceback

import numpy as np

_worker_info = None


class WorkerInfo:
    """ref: io/dataloader/worker.py WorkerInfo."""

    def __init__(self, id, num_workers, dataset, seed=None):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


def get_worker_info():
    """Inside a worker process: this worker's info; None in the parent
    (ref: paddle.io.get_worker_info)."""
    return _worker_info


def np_collate(batch):
    """Numpy-level default collate — same nesting rules as
    default_collate_fn but never constructs Tensors (workers must stay off
    jax)."""
    sample = batch[0]
    if hasattr(sample, "_data"):  # Tensor snuck into a dataset: view as np
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(np_collate(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: np_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.asarray(batch)


def _tree_to_numpy(obj):
    """Force results to numpy before pickling back: Tensor leaves carry
    device buffers that neither pickle nor belong in a forked child."""
    if hasattr(obj, "_data"):
        return np.asarray(obj._data)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_numpy(v) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, collate_fn, index_queue, result_queue, worker_id,
                 num_workers, worker_init_fn, base_seed):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset,
                              seed=(base_seed + worker_id
                                    if base_seed is not None else None))
    np.random.seed(((base_seed or 0) + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        try:
            worker_init_fn(worker_id)
        except Exception:  # noqa: BLE001
            result_queue.put((-1, "error", traceback.format_exc()))
            return
    while True:
        task = index_queue.get()
        if task is None:
            break
        seq, indices = task
        try:
            samples = [dataset[i] for i in indices]
            result_queue.put((seq, "ok", _tree_to_numpy(collate_fn(samples))))
        except Exception:  # noqa: BLE001
            result_queue.put((seq, "error", traceback.format_exc()))


class ProcessPool:
    """Order-preserving multiprocess fetch pool over a map-style dataset."""

    def __init__(self, dataset, collate_fn, num_workers, prefetch_factor=2,
                 worker_init_fn=None, base_seed=None, timeout=0):
        ctx = multiprocessing.get_context("fork")
        self.num_workers = num_workers
        self.prefetch = max(prefetch_factor, 1)
        # user-facing stuck-worker bound (DataLoader timeout=): 0 waits
        # forever (dead-worker detection still applies via the 5s poll)
        self.timeout = float(timeout or 0)
        if base_seed is None:
            # fresh randomness per pool (per epoch): augmentation must not
            # replay byte-identical across epochs
            base_seed = int.from_bytes(__import__("os").urandom(4), "little")
        self._index_queues = [ctx.SimpleQueue() for _ in range(num_workers)]
        self._result_queue = ctx.Queue()
        self._workers = []
        for wid in range(num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(dataset, collate_fn, self._index_queues[wid],
                      self._result_queue, wid, num_workers, worker_init_fn,
                      base_seed),
                daemon=True)
            p.start()
            self._workers.append(p)
        self._alive = True

    def run(self, index_batches):
        """Yield collated batches in order over `index_batches` (an iterable
        of index lists)."""
        it = iter(enumerate(index_batches))
        outstanding = 0
        next_worker = 0
        next_yield = 0
        buffered = {}

        def dispatch_one():
            nonlocal outstanding, next_worker
            try:
                seq, indices = next(it)
            except StopIteration:
                return False
            self._index_queues[next_worker].put((seq, list(indices)))
            next_worker = (next_worker + 1) % self.num_workers
            outstanding += 1
            return True

        for _ in range(self.num_workers * self.prefetch):
            if not dispatch_one():
                break
        import time as _time
        import queue as _queue
        t_last = _time.monotonic()
        while outstanding:
            poll = 5.0 if not self.timeout else min(5.0, self.timeout)
            try:
                seq, status, payload = self._result_queue.get(timeout=poll)
                t_last = _time.monotonic()
            except _queue.Empty:
                dead = [p for p in self._workers if not p.is_alive()]
                if dead:
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader worker(s) died without a result "
                        f"(exitcodes {[p.exitcode for p in dead]}) — "
                        f"OOM-kill or crash in the dataset/transform code")
                if self.timeout and _time.monotonic() - t_last > self.timeout:
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader worker produced no batch within "
                        f"timeout={self.timeout}s — stuck dataset/"
                        f"transform code in a live worker process")
                continue
            outstanding -= 1
            if status == "error":
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader worker failed:\n{payload}")
            buffered[seq] = payload
            dispatch_one()
            while next_yield in buffered:
                yield buffered.pop(next_yield)
                next_yield += 1

    def shutdown(self):
        if not self._alive:
            return
        self._alive = False
        for q in self._index_queues:
            try:
                q.put(None)
            except Exception:  # noqa: BLE001
                pass
        for p in self._workers:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:  # noqa: BLE001
            pass
