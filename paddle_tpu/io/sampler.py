"""Samplers (ref: python/paddle/io/dataloader/sampler.py, batch_sampler.py,
distributed_sampler → DistributedBatchSampler)."""
from __future__ import annotations

import numpy as np

from ..framework.random import next_key


def _perm(n):
    import jax
    return np.asarray(jax.random.permutation(next_key(), n))


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            import jax
            idx = np.asarray(jax.random.randint(next_key(), (self.num_samples,), 0, n))
            return iter(idx.tolist())
        return iter(_perm(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.asarray(self.indices)[_perm(len(self.indices))].tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        import jax
        key = next_key()
        idx = np.asarray(jax.random.choice(
            key, len(self.weights), (self.num_samples,),
            replace=self.replacement, p=p))
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        assert dataset is not None or sampler is not None
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """ref: io/dataloader/distributed_sampler — per-host shard of the global
    index stream. On single-controller TPU, the global batch is fed whole and
    GSPMD shards it over 'dp'; this sampler exists for multi-HOST input
    pipelines, where each host loads 1/num_replicas of the data."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        import jax
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.nranks = num_replicas if num_replicas is not None else \
            jax.process_count()
        self.local_rank = rank if rank is not None else jax.process_index()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            import jax
            key = jax.random.key(self.epoch)
            indices = np.asarray(jax.random.permutation(key, n))
            self.epoch += 1
        indices = np.concatenate([indices, indices[:self.total_size - n]])
        local = indices[self.local_rank::self.nranks]
        batch = []
        for idx in local.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
