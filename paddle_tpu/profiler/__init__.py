"""paddle.profiler parity on top of jax.profiler (ref: python/paddle/profiler).

The reference collects host/device events into its own timeline; on TPU the
source of truth is XLA's xplane trace. Profiler here drives
jax.profiler.start_trace/stop_trace (viewable in TensorBoard / Perfetto) and
keeps a host-side RecordEvent timeline exported as chrome tracing JSON.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum

import jax


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Step-state scheduler (ref profiler/utils.py make_scheduler)."""

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


_host_events = []
_events_lock = threading.Lock()
_nesting = threading.local()  # per-thread active RecordEvent depth


class RecordEvent:
    """Context/annotation for a named host-side region; also forwards to
    jax.profiler.TraceAnnotation so it appears in the xplane trace.

    Instances are RE-ENTERABLE: each ``begin()`` opens a fresh
    TraceAnnotation onto a per-instance stack (the seed silently reused
    one annotation, so ``begin(); begin()`` corrupted both regions), and
    nested regions — same instance or different — export their per-thread
    nesting depth in the chrome trace (``args.depth``). ``end()`` without
    a matching ``begin()`` raises instead of emitting garbage."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._stack = []        # (t0_ns, TraceAnnotation, depth)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()

    def begin(self):
        ann = jax.profiler.TraceAnnotation(self.name)
        ann.__enter__()
        depth = getattr(_nesting, "depth", 0)
        _nesting.depth = depth + 1
        self._stack.append((time.perf_counter_ns(), ann, depth,
                            threading.get_ident()))

    def end(self):
        if not self._stack:
            raise RuntimeError(
                f"RecordEvent({self.name!r}).end() without a matching "
                f"begin()")
        t0, ann, depth, tid = self._stack.pop()
        if threading.get_ident() == tid:
            # only the beginning thread's nesting counter moves: an end()
            # from another thread must not decrement that thread's depth
            # (and the beginner's counter re-syncs at its next begin/end)
            _nesting.depth = max(0, getattr(_nesting, "depth", 1) - 1)
        ann.__exit__(None, None, None)
        with _events_lock:
            _host_events.append(
                {"name": self.name, "ph": "X", "pid": os.getpid(),
                 "tid": threading.get_ident(),
                 "ts": t0 / 1000.0,
                 "dur": (time.perf_counter_ns() - t0) / 1000.0,
                 "args": {"depth": depth}})


def export_chrome_tracing(dir_name, worker_name=None):
    """Returns an on_trace_ready callback writing chrome tracing JSON."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        path = os.path.join(dir_name, f"{worker_name or 'worker'}.json")
        with _events_lock:
            events = list(_host_events)
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        prof._chrome_trace_path = path

    return handler


class Profiler:
    """paddle.profiler.Profiler parity: scheduler-driven trace capture."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 trace_dir=None):
        self.scheduler = (make_scheduler(closed=0, ready=0, record=scheduler[1] - scheduler[0],
                                         skip_first=scheduler[0])
                          if isinstance(scheduler, (tuple, list)) else scheduler)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_dir = trace_dir or "/tmp/paddle_tpu_profile"
        self._step = 0
        self._tracing = False
        self._step_times = []
        self._t_last = None

    def start(self):
        self._t_last = time.perf_counter()
        if not self.timer_only:
            state = self.scheduler(self._step) if self.scheduler else ProfilerState.RECORD
            if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
                self._start_trace()

    def _start_trace(self):
        if not self._tracing:
            os.makedirs(self.trace_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self.trace_dir)
                self._tracing = True
            except Exception:
                self._tracing = False

    def _stop_trace(self):
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            finally:
                self._tracing = False

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t_last is not None:
            self._step_times.append(now - self._t_last)
        self._t_last = now
        self._step += 1
        if self.timer_only or self.scheduler is None:
            return
        state = self.scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_trace()
        else:
            if self._tracing:
                self._stop_trace()
                if state == ProfilerState.CLOSED and self.on_trace_ready:
                    self.on_trace_ready(self)
        if state == ProfilerState.RECORD_AND_RETURN and self.on_trace_ready:
            self.on_trace_ready(self)

    def stop(self):
        self._stop_trace()
        if self.on_trace_ready and not self.timer_only:
            self.on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times) * 1e3
        return (f"steps: {len(ts)}  avg: {ts.mean():.2f}ms  p50: "
                f"{np.percentile(ts, 50):.2f}ms  p99: {np.percentile(ts, 99):.2f}ms")


# -- eager dispatch-cache counters -------------------------------------------
# The jit-cached eager dispatch (dispatch.py) counts every apply() call,
# LRU hit/miss, actual XLA (re)trace, and uncacheable fallback. hit_rate()
# is the steady-state fraction of cached dispatches that re-used compiled
# code — the first metric to look at when the dygraph path is slow.

def dispatch_counters():
    """Snapshot of the eager dispatch-cache counters as a dict, plus the
    derived steady-state `hit_rate` and current `cache_entries`. (Thin
    view over the observability registry's "dispatch" family — same dict,
    also reachable via ``observability.snapshot()`` / Prometheus.)"""
    from ..observability import collect
    return collect("dispatch")


def reset_dispatch_counters():
    from ..dispatch import reset_cache_stats
    reset_cache_stats()


def dispatch_cache_summary():
    """One-line human-readable dispatch-cache report."""
    c = dispatch_counters()
    return (f"dispatches: {c['dispatches']}  cached: {c['cached_calls']}  "
            f"traces: {c['traces']}  fallbacks: {c['fallbacks']}  "
            f"hit-rate: {c['hit_rate'] * 100:.1f}%  "
            f"entries: {c['cache_entries']}")


# -- gradient-communication counters ----------------------------------------
# The explicit grad-comm layer (distributed/grad_comm.py) has a static
# collective schedule per compiled TrainStep; every executed step records its
# wire bytes (reduce vs gather, by dtype), collective count, bucket count and
# bucket fill here. The first thing to look at when a DP step is
# communication-bound — and the evidence hook for the reduce-scatter and
# quantized-reduce wins.

def comm_counters():
    """Snapshot of the gradient-communication counters: reduce_bytes (+ by
    dtype), gather_bytes, collectives, buckets, bucket_fill, steps — plus
    the per-axis `backend` label ({'dp': 'ring'|'fused'}) and
    `fused_dispatches` (Pallas kernel launches of the fused backend), so
    counter gates can assert which backend actually ran. (Thin view over
    the registry's "comm" family.)"""
    from ..observability import collect
    return collect("comm")


def reset_comm_counters():
    from ..distributed import grad_comm
    grad_comm.reset_comm_counters()


def comm_summary():
    """One-line human-readable gradient-communication report. The backend
    label covers every axis with an explicit schedule this process ran —
    dp (grad_comm) plus the pp pipeline ledger's label when pipelined
    steps were recorded."""
    c = comm_counters()
    by = " ".join(f"{k}:{v / 1e6:.2f}MB"
                  for k, v in sorted(c["reduce_bytes_by_dtype"].items()))
    label = dict(c["backend"])
    label.update(pp_comm_counters()["backend"])
    backend = ",".join(f"{a}={b}" for a, b in sorted(label.items())) \
        or "gspmd"
    return (f"steps: {c['steps']}  backend: {backend}  "
            f"collectives: {c['collectives']}  "
            f"reduce: {c['reduce_bytes'] / 1e6:.2f}MB ({by})  "
            f"gather: {c['gather_bytes'] / 1e6:.2f}MB  "
            f"buckets: {c['buckets']}  fill: {c['bucket_fill'] * 100:.1f}%  "
            f"fused-dispatches: {c['fused_dispatches']}")


# -- tensor-parallel (mp-axis) communication counters ------------------------
# The explicit mp schedule (distributed/tp_overlap.py; FLAGS_sequence_parallel
# / FLAGS_mp_overlap) has a static per-step collective ledger: reduce-scatter
# and all-gather wire bytes, collective counts, ring ppermute hops, and the
# inter-block activation residency per device. Recorded per executed step —
# the evidence hook for "per-block mp all-reduces replaced by RS+AG" and the
# 1/mp activation claim.


def mp_comm_counters():
    """Snapshot of the mp-axis schedule counters: rs_bytes, ag_bytes,
    wire_bytes, collectives, ppermute_hops, activation_bytes, steps — plus
    the per-axis `backend` label ({'mp': 'rsag'|'ring'|'fused'}) and
    `fused_dispatches` (Pallas GEMM+collective kernel launches per the
    static forward schedule), so counter gates can assert which backend
    actually ran. (Thin view over the registry's "mp_comm" family.)"""
    from ..observability import collect
    return collect("mp_comm")


def reset_mp_comm_counters():
    from ..distributed import tp_overlap
    tp_overlap.reset_mp_counters()


def mp_comm_summary():
    """One-line human-readable mp-axis communication report (the backend
    label also names the pp axis when pipelined steps were recorded — the
    two explicit model-parallel schedules compose in one region)."""
    c = mp_comm_counters()
    label = dict(c["backend"])
    label.update(pp_comm_counters()["backend"])
    backend = ",".join(f"{a}={b}" for a, b in sorted(label.items())) \
        or "gspmd"
    return (f"steps: {c['steps']}  backend: {backend}  "
            f"collectives: {c['collectives']}  "
            f"rs: {c['rs_bytes'] / 1e6:.2f}MB  "
            f"ag: {c['ag_bytes'] / 1e6:.2f}MB  "
            f"ppermute-hops: {c['ppermute_hops']}  "
            f"fused-dispatches: {c['fused_dispatches']}  "
            f"act/block: {c['activation_bytes'] / 1e6:.3f}MB")


# -- pipeline-parallel (pp-axis) communication counters ----------------------
# The explicit pp schedule (distributed/pipeline.py ring/fused backends;
# FLAGS_comm_backend='pp=...') has a static per-step boundary ledger:
# boundary activation/cotangent wire bytes, explicit ppermute hops, fused
# boundary-kernel dispatches and the schedule's bubble-fraction estimate.
# Recorded per executed HybridTrainStep — the evidence hook for "boundary
# sends overlapped into the next tick's stage compute" and the fused
# last-GEMM RDMA epilogue.


def pp_comm_counters():
    """Snapshot of the pp-axis schedule counters: boundary_bytes,
    ppermute_hops, fused_dispatches, steps, plus the schedule shape
    (schedule, stages, microbatches, bubble_fraction — the idle-slot
    estimate, gpipe (S-1)/(M+S-1), 1f1b (2S-2)/(M+2S-2)) and the per-axis
    `backend` label ({'pp': 'gspmd'|'ring'|'fused'}), so counter gates can
    assert which backend actually ran. (Thin view over the registry's
    "pp_comm" family.)"""
    from ..observability import collect
    return collect("pp_comm")


def reset_pp_comm_counters():
    from ..distributed import pipeline
    pipeline.reset_pp_counters()


def pp_comm_summary():
    """One-line human-readable pp-axis communication report."""
    c = pp_comm_counters()
    backend = ",".join(f"{a}={b}" for a, b in sorted(c["backend"].items())) \
        or "gspmd"
    return (f"steps: {c['steps']}  backend: {backend}  "
            f"schedule: {c['schedule'] or '-'}  "
            f"stages: {c['stages']}  microbatches: {c['microbatches']}  "
            f"boundary: {c['boundary_bytes'] / 1e6:.2f}MB  "
            f"ppermute-hops: {c['ppermute_hops']}  "
            f"fused-dispatches: {c['fused_dispatches']}  "
            f"bubble: {c['bubble_fraction'] * 100:.1f}%")


# -- fault-tolerance counters -------------------------------------------------
# The compiled anomaly guard (jit/train_step.py, FLAGS_anomaly_policy), the
# hardened CheckpointManager (incubate/checkpoint.py) and the chaos harness
# (utils/fault_injection.py) each keep a ledger. `host_syncs` is the audit
# trail for the guard's zero-extra-sync contract: one combined (loss,
# step_ok...) fetch per UPDATE step — host_syncs == steps at
# accumulate_steps=1, and steps/k under accumulation (micro flags ride to
# the fire boundary in the same fetch). Anything above that means a sync
# snuck in.


def fault_counters():
    """Snapshot of the fault-tolerance counters: anomaly guard (steps,
    host_syncs, bad_steps, skipped_updates, rollbacks), checkpoint manager
    (saves, save_retries, quarantined, restore_fallbacks, preempt_saves)
    and injected-fault stats. (Thin view over the registry's "fault"
    family.)"""
    from ..observability import collect
    return collect("fault")


def reset_fault_counters():
    from ..jit import train_step as _ts
    from ..incubate import checkpoint as _ck
    _ts.reset_anomaly_counters()
    _ck.reset_ckpt_counters()


def fault_summary():
    """One-line human-readable fault-tolerance report (an ``sdc:``
    segment appears only when the integrity sentinel did any work)."""
    c = fault_counters()
    a, k = c["anomaly"], c["checkpoint"]
    line = (f"steps: {a['steps']}  host-syncs: {a['host_syncs']}  "
            f"bad: {a['bad_steps']}  skipped: {a['skipped_updates']}  "
            f"rollbacks: {a['rollbacks']}  saves: {k['saves']}  "
            f"retries: {k['save_retries']}  quarantined: {k['quarantined']}  "
            f"preempt-saves: {k['preempt_saves']}")
    from ..distributed import integrity as _integrity
    s = _integrity.sdc_counters()
    if any(s.values()):
        line += (f"  sdc: checks={s['fingerprint_checks']} "
                 f"mismatches={s['fingerprint_mismatches']} "
                 f"repairs={s['repairs']} "
                 f"redispatches={s['repair_redispatches']} "
                 f"scrubs={s['scrubs']} rot={s['rot_found']} "
                 f"quarantined={s['quarantined_ranks']}")
    return line


# -- serving counters ---------------------------------------------------------
# The continuous-batching engine (serving/engine.py) ledgers every request,
# prefill call/chunk, decode iteration and token. The trace counters
# (prefill/decode for the pooled layout; paged_traces/copy_traces for the
# paged layout's fused step and CoW page copy) are the no-recompile audit
# trail: each jitted body counts only when actually traced, so after warmup
# the counts freeze — joins, evicts, chunked admissions, CoW remaps and
# sampling-param changes must not move them (and an Engine RESTORED from a
# snapshot re-dispatches the warm executables, so a restore must not move
# them either). TTFT/token-latency percentiles, tokens/s, slot occupancy
# and queue depth are the serving SLO surface; the paged layout adds page
# occupancy, prefix-cache hit rate / tokens reused, chunk-interleave
# counters and per-prefill padded-token waste. The self-healing runtime
# (engine snapshots + ServingSupervisor) adds the recovery ledger:
# snapshots/snapshot_restores, preempt_drains, requeued/replayed,
# respawns, stale_failovers, rolling_restarts — and "dropped", which must
# stay 0 through any kill/preemption/rolling-restart story.


def serving_counters():
    """Snapshot of the serving-engine counters: request lifecycle
    (submitted/admitted/completed/expired/rejected), executable calls and
    traces, tokens_out, ttft_p50/p99, token_latency_p50, tokens_per_s,
    occupancy, queue depth — plus the paged-KV ledger (page_occupancy,
    prefix_hit_rate, prefix_tokens_reused, chunk_steps, cow_copies,
    prefill_waste_mean). (Thin view over the registry's "serving"
    family.)"""
    from ..observability import collect
    return collect("serving")


def reset_serving_counters():
    from ..serving import metrics
    metrics.reset_serving_counters()


def serving_summary():
    """One-line human-readable serving report."""
    from ..serving import metrics
    return metrics.serving_summary()


def recovery_counters():
    """Self-healing subset of the serving ledger: engine snapshots taken /
    restored, preemption drains, requests requeued / replayed, replica
    respawns, stale-heartbeat failovers, rolling restarts, and dropped
    (the invariant: 0). (Thin view over the registry's "recovery"
    family.)"""
    from ..observability import collect
    return collect("recovery")


def elastic_counters():
    """Topology-elastic ledger: mesh shrinks/grows/reforms and snapshot
    restores the ElasticMeshSupervisor performed, resume latency, steps
    re-executed after a restore, live active-dp/world/failed-ranks gauges,
    plus the reshard-on-load counters (checkpoints loaded across a
    topology change, leaves moved, rejected mismatched loads). (Thin view
    over the registry's "elastic" family.)"""
    from ..observability import collect
    return collect("elastic")


def reset_elastic_counters():
    from ..distributed import elastic as _el
    from ..distributed import topology as _topo
    _el.reset_elastic_counters()
    _topo.reset_reshard_counters()


def elastic_summary():
    """One-line human-readable topology-elastic report (training mesh
    reforms plus, when a topology-elastic serving fleet ran, the serving
    group-reform segment)."""
    c = elastic_counters()
    serving = ""
    if c.get("group_reforms") or c.get("degraded_groups"):
        serving = (f"  serving: {c['group_reforms']} group-reforms "
                   f"({c['grow_backs']} grow-backs)  "
                   f"degraded-groups: {c['degraded_groups']}  "
                   f"chips-lost: {c['serving_chips_lost']}  "
                   f"reform: {c['reform_latency_s_last'] * 1e3:.0f}ms")
    return (f"dp: {c['active_dp']}/{c['world_size']}  "
            f"failed-ranks: {c['failed_ranks']}  "
            f"shrinks: {c['shrinks']}  grows: {c['grows']}  "
            f"restores: {c['elastic_restores']}  "
            f"resharded-loads: {c['resharded_loads']} "
            f"({c['resharded_leaves']} leaves)  "
            f"steps-lost: {c['steps_lost']}  "
            f"resume: {c['resume_latency_s_last'] * 1e3:.0f}ms" + serving)


def benchmark():
    """Step-timer handle (ref profiler.utils.benchmark)."""
    return _Benchmark()


class _Benchmark:
    def __init__(self):
        self._times = []
        self._t = None

    def begin(self):
        self._t = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t is not None:
            self._times.append(now - self._t)
        self._t = now

    def end(self):
        pass

    def step_info(self, unit="ms"):
        import numpy as np
        if not self._times:
            return "n/a"
        return f"avg {np.mean(self._times) * 1e3:.3f} ms/step"


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


class SortedKeys:
    """ref: profiler/profiler_statistic.py SortedKeys enum."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """ref: profiler SummaryView enum."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name, worker_name=None):
    """The jax profiler's native artifact is xplane protobuf; exporting
    chrome tracing also materializes the .xplane.pb files under dir_name."""
    return export_chrome_tracing(dir_name, worker_name)
