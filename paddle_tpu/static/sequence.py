"""Sequence ops (ref: python/paddle/static/nn/sequence_lod.py).

The reference operates on LoD tensors — ragged sequences packed flat with
level-of-detail offsets, a CPU-era layout XLA cannot tile. The TPU-native
layout is dense padding: every op here takes `x` as a padded batch
[B, T, ...] plus an optional `seq_len` [B] of valid lengths (None = all T
valid). That is also what `sequence_pad`/`sequence_unpad` convert between:
unpad returns the ragged python list the LoD form represents.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data, wrap
from ..dispatch import apply


def _data_len(x, seq_len):
    xd = as_tensor_data(x)
    B, T = xd.shape[0], xd.shape[1]
    if seq_len is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = jnp.asarray(as_tensor_data(seq_len), jnp.int32).reshape(B)
    return xd, lens, B, T


def _valid_mask(lens, T):
    return jnp.arange(T)[None, :] < lens[:, None]  # [B, T]


def sequence_softmax(x, seq_len=None, name=None):
    """Softmax over each sequence's valid steps (padding gets 0 weight)."""
    xd, lens, B, T = _data_len(x, seq_len)

    def f(xv):
        mask = _valid_mask(lens, T)
        shaped = mask if xv.ndim == 2 else mask[..., None]
        logits = jnp.where(shaped, xv, -jnp.inf)
        out = jax.nn.softmax(logits, axis=1)
        return jnp.where(shaped, out, 0.0)

    return apply(f, x, op_name="sequence_softmax")


def sequence_pool(x, pool_type="average", seq_len=None, pad_value=0.0):
    """Pool each sequence to one vector: average/sum/max/min/sqrt/first/last
    (ref sequence_lod.py sequence_pool)."""
    xd, lens, B, T = _data_len(x, seq_len)
    pt = pool_type.lower()

    def f(xv):
        mask = _valid_mask(lens, T)
        m = mask if xv.ndim == 2 else mask[..., None]
        cnt = jnp.maximum(lens, 1).astype(xv.dtype)
        cshape = (B,) + (1,) * (xv.ndim - 2)
        if pt == "sum":
            return jnp.where(m, xv, 0).sum(axis=1)
        if pt in ("average", "mean"):
            return jnp.where(m, xv, 0).sum(axis=1) / cnt.reshape(cshape)
        if pt == "sqrt":
            return jnp.where(m, xv, 0).sum(axis=1) / \
                jnp.sqrt(cnt).reshape(cshape).astype(xv.dtype)
        if pt == "max":
            out = jnp.where(m, xv, -jnp.inf).max(axis=1)
            return jnp.where(jnp.isneginf(out), pad_value, out)
        if pt == "min":
            out = jnp.where(m, xv, jnp.inf).min(axis=1)
            return jnp.where(jnp.isposinf(out), pad_value, out)
        if pt == "first":
            return xv[:, 0]
        if pt == "last":
            idx = jnp.maximum(lens - 1, 0)
            return jnp.take_along_axis(
                xv, idx.reshape((B,) + (1,) * (xv.ndim - 1)), axis=1)[:, 0]
        raise ValueError(f"unknown pool_type {pool_type!r}")

    return apply(f, x, op_name=f"sequence_pool_{pt}")


def sequence_first_step(x, seq_len=None):
    return sequence_pool(x, "first", seq_len)


def sequence_last_step(x, seq_len=None):
    return sequence_pool(x, "last", seq_len)


def sequence_reverse(x, seq_len=None, name=None):
    """Reverse each sequence's valid prefix in place; padding stays put."""
    xd, lens, B, T = _data_len(x, seq_len)

    def f(xv):
        pos = jnp.arange(T)[None, :]
        rev = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
        return jnp.take_along_axis(
            xv, rev.reshape((B, T) + (1,) * (xv.ndim - 2)), axis=1)

    return apply(f, x, op_name="sequence_reverse")


def sequence_concat(input, name=None):
    """Concatenate sequences element-wise along time (ref sequence_concat):
    padded analog concatenates along T. Routed through apply so the tape
    records it."""
    return apply(lambda *vs: jnp.concatenate(vs, axis=1), *input,
                 op_name="sequence_concat")


def sequence_slice(input, offset, length, name=None):
    """Per-sequence slice [offset, offset+length) along time."""
    xd = as_tensor_data(input)
    off = jnp.asarray(as_tensor_data(offset), jnp.int32).reshape(-1)
    ln = np.asarray(jax.device_get(as_tensor_data(length))).reshape(-1)
    L = int(ln.max())
    B, T = xd.shape[0], xd.shape[1]

    def f(xv):
        idx = off[:, None] + jnp.arange(L)[None, :]
        idx = jnp.clip(idx, 0, T - 1)
        out = jnp.take_along_axis(
            xv, idx.reshape((B, L) + (1,) * (xv.ndim - 2)), axis=1)
        mask = jnp.arange(L)[None, :] < jnp.asarray(ln)[:, None]
        return jnp.where(mask if xv.ndim == 2 else mask[..., None], out, 0)

    return apply(f, input, op_name="sequence_slice")


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each row of x to match y's per-sequence lengths
    (padded analog: tile x rows along a new time axis of y's T)."""
    xd = as_tensor_data(x)
    yd = as_tensor_data(y)
    T = yd.shape[1]

    def f(xv):
        return jnp.repeat(xv[:, None], T, axis=1) if xv.ndim == 2 else \
            jnp.broadcast_to(xv[:, None], (xv.shape[0], T) + xv.shape[1:])

    return apply(f, x, op_name="sequence_expand")


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Ragged python list -> (padded [B, maxlen, ...], lengths [B])
    (ref sequence_pad returns (Out, Length))."""
    seqs = [np.asarray(jax.device_get(as_tensor_data(s))) for s in x] \
        if isinstance(x, (list, tuple)) else \
        [np.asarray(jax.device_get(as_tensor_data(x)))]
    lens = np.asarray([s.shape[0] for s in seqs], np.int64)
    T = int(maxlen) if maxlen is not None else int(lens.max())
    pv = float(np.asarray(jax.device_get(as_tensor_data(pad_value))).reshape(-1)[0])
    tail = seqs[0].shape[1:]
    out = np.full((len(seqs), T) + tail, pv, seqs[0].dtype)
    for i, s in enumerate(seqs):
        out[i, :min(s.shape[0], T)] = s[:T]
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(lens))


def sequence_unpad(x, length, name=None):
    """(padded, lengths) -> list of ragged arrays (the LoD content)."""
    xd = np.asarray(jax.device_get(as_tensor_data(x)))
    lens = np.asarray(jax.device_get(as_tensor_data(length))).reshape(-1)
    return [wrap(jnp.asarray(xd[i, :int(l)])) for i, l in enumerate(lens)]


def sequence_reshape(input, new_dim, name=None):
    """Reshape the trailing feature dim, redistributing time steps."""
    xd = as_tensor_data(input)
    B = xd.shape[0]

    def f(xv):
        return xv.reshape(B, -1, new_dim)

    return apply(f, input, op_name="sequence_reshape")


def sequence_scatter(input, index, updates, name=None):
    """Scatter updates into per-sequence time positions."""
    xd = as_tensor_data(input)
    B = xd.shape[0]

    def f(xv, upd):
        idx = jnp.asarray(as_tensor_data(index), jnp.int32).reshape(B, -1)
        return xv.at[jnp.arange(B)[:, None], idx].add(upd)

    return apply(f, input, updates, op_name="sequence_scatter")


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding windows of ids along time (ref sequence_enumerate)."""
    xd = as_tensor_data(input)
    B, T = xd.shape[0], xd.shape[1]

    def f(xv):
        pad = jnp.full((B, win_size - 1), pad_value, xv.dtype)
        ext = jnp.concatenate([xv, pad], axis=1)
        return jnp.stack([ext[:, i:i + T] for i in range(win_size)], axis=-1)

    return apply(f, input, op_name="sequence_enumerate")


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Temporal convolution over padded sequences: window of `filter_size`
    steps -> Linear (ref sequence_conv's im2col + fc formulation)."""
    from .. import nn
    from .nn import _get_layer, _act
    xd = as_tensor_data(input)
    B, T, D = xd.shape
    layer = _get_layer(name, lambda: nn.Linear(
        D * filter_size, num_filters, weight_attr=param_attr,
        bias_attr=bias_attr))
    start = -(filter_size // 2) if padding_start is None else padding_start

    def windows(xv):
        padded = jnp.pad(xv, ((0, 0), (filter_size, filter_size), (0, 0)))
        cols = [padded[:, filter_size + start + i:
                       filter_size + start + i + T] for i in range(filter_size)]
        return jnp.concatenate(cols, axis=-1)  # [B, T, D*filter_size]

    win = apply(windows, input, op_name="sequence_conv_im2col")
    return _act(layer(win), act)


class StaticRNN:
    """Legacy static-graph RNN builder (ref fluid/layers StaticRNN) — the
    lax.scan era replacement is paddle_tpu.nn.RNN; this shim raises with
    guidance rather than half-working."""

    def __init__(self, name=None):
        raise NotImplementedError(
            "StaticRNN is the legacy static-graph unroller; use "
            "paddle_tpu.nn.SimpleRNN/LSTM/GRU (lax.scan) instead")
