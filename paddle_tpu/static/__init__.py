"""paddle_tpu.static — InputSpec + minimal static-graph parity surface.

The reference's static graph Program/Executor stack maps to XLA compilation;
`paddle_tpu.jit.to_static` is the supported route. InputSpec is kept since the
dygraph API uses it for signature declaration (ref: python/paddle/static/input.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.state import to_jnp_dtype


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = tuple(shape)
        self.dtype = to_jnp_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), tensor.dtype, name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)


from .extras import *  # noqa: E402,F401,F403
from .extras import __all__ as _extras_all  # noqa: E402
from . import nn  # noqa: E402,F401
