"""Static-graph API surface (ref: python/paddle/static/__init__.py).

Design note: the reference's Program/Executor stack is a graph IR + C++
interpreter; on TPU that role is played by jax tracing + XLA. This module
keeps the reference's static API *names and call patterns* working by
backing them with the traced-function machinery:

* a `Program` records `to_static`-style callables and their parameters,
* `Executor.run` executes a traced program (or an inference artifact),
* `save/load_inference_model` bridge to the StableHLO deploy path
  (paddle_tpu.inference),
* pure utilities (EMA, gradients, py_func, places, metrics) are real.

IPU-specific entries raise — no such hardware path on TPU (SURVEY §2
out-of-scope list).
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, Parameter, as_tensor_data, wrap
from . import InputSpec

__all__ = [
    "append_backward", "gradients", "Executor", "global_scope", "scope_guard",
    "BuildStrategy", "CompiledProgram", "ExecutionStrategy", "Print",
    "py_func", "name_scope", "program_guard", "WeightNormParamAttr",
    "ExponentialMovingAverage", "default_main_program",
    "default_startup_program", "Program", "data", "Variable",
    "save_inference_model", "load_inference_model", "serialize_program",
    "serialize_persistables", "save_to_file", "deserialize_program",
    "deserialize_persistables", "load_from_file", "normalize_program",
    "load_program_state", "set_program_state", "cpu_places", "cuda_places",
    "xpu_places", "create_global_var", "create_parameter", "accuracy", "auc",
    "device_guard", "ipu_shard_guard", "IpuCompiledProgram", "IpuStrategy",
    "set_ipu_shard", "ctr_metric_bundle", "exponential_decay", "save", "load",
]


class Variable(InputSpec):
    """Placeholder variable (static.data result). Carries name/shape/dtype;
    feeding happens by name through Executor.run."""


def data(name, shape, dtype="float32", lod_level=0):
    return Variable([d if d is not None else -1 for d in shape], dtype, name)


class Program:
    """Recorded computation: a list of (name, traced callable) plus state.
    XLA is the optimizer/scheduler; this object is the user-facing handle."""

    def __init__(self):
        self.functions = {}
        self.state = {}
        self.random_seed = 0

    def clone(self, for_test=False):
        p = Program()
        p.functions = dict(self.functions)
        p.state = dict(self.state)
        return p

    def global_block(self):
        return self

    # block-protocol shims used by reference-style code
    @property
    def blocks(self):
        return [self]

    def state_dict(self, mode="all", scope=None):
        return dict(self.state)

    def set_state_dict(self, sd, scope=None):
        self.state.update(sd)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    old = (_default_main, _default_startup)
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = old


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, None)

    def find_var(self, name):
        return self.get(name)


_global_scope = _Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old


class Executor:
    """Runs traced callables / loaded inference artifacts. `place` is kept
    for signature parity; XLA chooses the backend."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kw):
        feed = feed or {}
        if hasattr(program, "run"):  # Predictor from load_inference_model
            outs = program.run(*feed.values())
            return outs
        if isinstance(program, Program) and program.functions:
            results = []
            for fn in program.functions.values():
                results.append(fn(**feed))
            return results
        if callable(program):
            return program(**feed)
        return []


class BuildStrategy:
    """Config shell (XLA performs fusion/memory planning internally)."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, name):
        return getattr(self.__dict__["program"], name)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Dygraph-backed: runs autograd and returns (param, grad) pairs."""
    loss.backward()
    params = parameter_list or []
    return [(p, p.grad) for p in params]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.engine import grad as _grad
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print that also works under jit (jax.debug.print)."""
    a = as_tensor_data(input)
    jax.debug.print((message or "") + " {x}", x=a)
    return wrap(a)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host callback op (ref static.py_func) via jax.pure_callback."""
    xs = [as_tensor_data(t) for t in (x if isinstance(x, (list, tuple)) else [x])]
    sample = out if not isinstance(out, (list, tuple)) else out[0]
    sds = jax.ShapeDtypeStruct(tuple(sample.shape), jnp.dtype(sample.dtype))
    res = jax.pure_callback(lambda *a: np.asarray(func(*a)), sds, *xs)
    return wrap(res)


class WeightNormParamAttr:
    """ref: static.WeightNormParamAttr — carried metadata; weight-norm
    reparameterization is applied by nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


class ExponentialMovingAverage:
    """EMA of trainable parameters with bias correction
    (ref: static/ema.py). apply()/restore() swap shadow weights in/out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = decay
        self._step = 0
        self._shadow = {}
        self._backup = {}
        self._params = []

    def register(self, parameters):
        self._params = list(parameters)
        for p in self._params:
            self._shadow[id(p)] = jnp.array(p._data)

    def update(self, parameters=None):
        if parameters is not None and not self._params:
            self.register(parameters)
        self._step += 1
        d = min(self.decay, (1 + self._step) / (10 + self._step))
        for p in self._params:
            prev = self._shadow.get(id(p), p._data)
            self._shadow[id(p)] = d * prev + (1 - d) * p._data

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            p._data = self._shadow[id(p)].astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
        self._backup = {}


# -- deploy bridge ----------------------------------------------------------

def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kw):
    """Bridge to the StableHLO deploy path: `program` (or fetch_vars[0]'s
    bound layer) must be a Layer; feed_vars carry the input specs."""
    from .. import inference as inf
    layer = kw.get("layer") or program
    if layer is None or not hasattr(layer, "forward"):
        raise ValueError(
            "save_inference_model needs the Layer (pass program=layer); the "
            "graph-free reference signature cannot be reconstructed from "
            "fetch_vars under eager tracing")
    specs = [v if isinstance(v, InputSpec) else
             InputSpec(v.shape, v.dtype, getattr(v, "name", None))
             for v in feed_vars]
    inf.save_inference_model(path_prefix, layer, specs)


def load_inference_model(path_prefix, executor=None, **kw):
    from .. import inference as inf
    pred = inf.load_inference_model(path_prefix)
    feeds = pred.get_input_names()
    return [pred, feeds, [f"out{i}" for i in range(1)]]


def serialize_program(feed_vars=None, fetch_vars=None, program=None, **kw):
    import pickle
    return pickle.dumps({"type": "paddle_tpu-program",
                         "state": getattr(program, "state", {})})


def deserialize_program(data):
    import pickle
    blob = pickle.loads(data)
    p = Program()
    p.state = blob.get("state", {})
    return p


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None, **kw):
    import pickle
    state = getattr(program, "state", {})
    return pickle.dumps({k: np.asarray(jax.device_get(as_tensor_data(v)))
                         for k, v in state.items()})


def deserialize_persistables(program, data, executor=None):
    import pickle
    program.state = pickle.loads(data)
    return program


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars=None, fetch_vars=None, **kw):
    return program


def save(program, model_path, protocol=4, **kw):
    save_to_file(model_path + ".pdmodel", serialize_program(program=program))
    save_to_file(model_path + ".pdparams",
                 serialize_persistables(program=program))


def load(program, model_path, executor=None, var_list=None):
    deserialize_persistables(program,
                             load_from_file(model_path + ".pdparams"))
    return program


def load_program_state(model_path, var_list=None):
    import pickle
    return pickle.loads(load_from_file(model_path + ".pdparams"))


def set_program_state(program, state_dict):
    program.state = dict(state_dict)


# -- places / metrics / misc -------------------------------------------------

def cpu_places(device_count=None):
    from ..framework.device import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..framework.device import TPUPlace
    ids = device_ids if device_ids is not None else range(jax.device_count())
    return [TPUPlace() for _ in ids]


def xpu_places(device_ids=None):
    raise NotImplementedError("XPU is out of scope on the TPU build "
                              "(SURVEY §2 not-rebuilt list)")


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    return Tensor(jnp.full(tuple(shape), value, dtype))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..framework.extras import create_parameter as _cp
    return _cp(shape, dtype, name, attr, is_bias, default_initializer)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy (ref: static/nn/metric.py accuracy) — delegates to the
    functional metric helper."""
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC via the trapezoid rule over score-sorted thresholds."""
    score = np.asarray(jax.device_get(as_tensor_data(input)))
    if score.ndim == 2 and score.shape[1] == 2:
        score = score[:, 1]
    y = np.asarray(jax.device_get(as_tensor_data(label))).reshape(-1)
    order = np.argsort(-score.reshape(-1))
    y_sorted = y[order]
    tps = np.cumsum(y_sorted)
    fps = np.cumsum(1 - y_sorted)
    tpr = tps / max(tps[-1], 1)
    fpr = fps / max(fps[-1], 1)
    a = float(np.trapezoid(tpr, fpr))
    t = wrap(jnp.asarray(a, jnp.float32))
    return t, t, [t]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    pred = as_tensor_data(input).reshape(-1)
    lab = as_tensor_data(label).reshape(-1).astype(jnp.float32)
    sqrerr = jnp.sum((pred - lab) ** 2)
    abserr = jnp.sum(jnp.abs(pred - lab))
    prob = jnp.sum(pred)
    q = jnp.sum(pred)
    pos = jnp.sum(lab)
    total = jnp.asarray(pred.shape[0], jnp.float32)
    return tuple(wrap(v) for v in (sqrerr, abserr, prob, q, pos, total))


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from ..optimizer.lr import ExponentialDecay
    # static-graph helper returns a scheduler in our world
    return ExponentialDecay(learning_rate, decay_rate)


@contextlib.contextmanager
def device_guard(device=None):
    """The reference pins ops to a device inside a program; XLA owns
    placement. Context preserved for API parity."""
    yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError("IPU is out of scope on the TPU build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU is out of scope on the TPU build")


class IpuStrategy:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU is out of scope on the TPU build")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU is out of scope on the TPU build")
