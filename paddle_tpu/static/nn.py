"""paddle.static.nn — static-graph layer builders (ref: python/paddle/
static/nn/common.py, control_flow.py, loss.py).

The reference's builders append ops + parameters to the current Program.
Here a Program is a handle over traced callables (static/extras.py), so
each builder creates the corresponding dygraph Layer — registered on the
default Program's state under `name` so a named builder called twice
reuses its parameters, like re-running a reference block — and applies it.
Control flow lowers to lax.cond/while_loop under tracing and plain Python
eagerly. The sequence ops live in sequence.py as dense-padded analogs of
the LoD originals (ragged LoD layouts have no TPU tiling).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor, as_tensor_data, wrap
from .extras import default_main_program


def _get_layer(name, factory):
    # anonymous builders create fresh params each call (reference Program
    # semantics) and are NOT cached — registering them would leak one layer
    # per call into Program.state
    if name is None:
        return factory()
    prog = default_main_program()
    # cache on a plain attribute, NOT prog.state: state holds persistable
    # tensors (serialize_persistables/state_dict iterate it)
    cache = getattr(prog, "_static_nn_layers", None)
    if cache is None:
        cache = prog._static_nn_layers = {}
    if name not in cache:
        cache[name] = factory()
    return cache[name]


def _act(x, activation):
    if activation is None:
        return x
    from ..nn import functional as F
    return getattr(F, activation)(x)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully-connected over flattened trailing dims (ref common.py fc)."""
    from .. import nn
    xs = list(as_tensor_data(x).shape)
    in_dim = int(np.prod(xs[num_flatten_dims:]))
    layer = _get_layer(name, lambda: nn.Linear(
        in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr))
    flat = as_tensor_data(x).reshape(tuple(xs[:num_flatten_dims]) + (in_dim,))
    return _act(layer(wrap(flat, stop_gradient=False)), activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    from .. import nn
    layer = _get_layer(name, lambda: nn.Embedding(
        size[0], size[1], padding_idx=padding_idx, weight_attr=param_attr))
    return layer(input)


sparse_embedding = embedding


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from .. import nn
    C = as_tensor_data(input).shape[1 if data_layout == "NCHW" else -1]
    layer = _get_layer(name, lambda: nn.BatchNorm(
        C, act=None, momentum=momentum, epsilon=epsilon,
        param_attr=param_attr, bias_attr=bias_attr, data_layout=data_layout))
    layer.training = not is_test
    layer._use_global_stats = use_global_stats or None
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn
    C = as_tensor_data(input).shape[1]
    layer = _get_layer(name, lambda: nn.InstanceNorm2D(
        C, epsilon=epsilon, weight_attr=param_attr, bias_attr=bias_attr))
    return layer(input)


def group_norm(input, groups, epsilon=1e-05, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from .. import nn
    C = as_tensor_data(input).shape[1]
    layer = _get_layer(name, lambda: nn.GroupNorm(
        groups, C, epsilon=epsilon, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _act(layer(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    from .. import nn
    shape = as_tensor_data(input).shape[begin_norm_axis:]
    layer = _get_layer(name, lambda: nn.LayerNorm(
        list(shape), epsilon=epsilon,
        weight_attr=param_attr if scale else False,
        bias_attr=bias_attr if shift else False))
    return _act(layer(input), act)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Global-stats normalization without learned affine by default
    (ref common.py data_norm)."""
    xd = as_tensor_data(input)
    mu = jnp.mean(xd, axis=0, keepdims=True)
    var = jnp.var(xd, axis=0, keepdims=True)
    return _act(wrap((xd - mu) * jax.lax.rsqrt(var + epsilon)), act)


def _conv(layer_cls, input, num_filters, filter_size, stride, padding,
          dilation, groups, param_attr, bias_attr, act, name, **extra):
    from .. import nn  # noqa: F401 — layer_cls resolved by caller
    C = as_tensor_data(input).shape[1]
    layer = _get_layer(name, lambda: layer_cls(
        C, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, **extra))
    return _act(layer(input), act)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    from .. import nn
    return _conv(nn.Conv2D, input, num_filters, filter_size, stride, padding,
                 dilation, groups, param_attr, bias_attr, act, name)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    from .. import nn
    return _conv(nn.Conv3D, input, num_filters, filter_size, stride, padding,
                 dilation, groups, param_attr, bias_attr, act, name)


def _transpose_filter_size(input, output_size, filter_size, stride, padding,
                           dilation, nd):
    """Reference semantics: filter_size may be omitted when output_size is
    given — derive k from out = (in-1)*stride - 2*pad + dilation*(k-1)+1."""
    if filter_size is not None:
        return filter_size
    if output_size is None:
        raise ValueError("conv transpose: give filter_size or output_size")
    tup = lambda v: (v,) * nd if isinstance(v, int) else tuple(v)  # noqa: E731
    outs, strides = tup(output_size), tup(stride)
    pads, dils = tup(padding), tup(dilation)
    spatial = as_tensor_data(input).shape[2:2 + nd]
    return tuple(
        (outs[i] - (spatial[i] - 1) * strides[i] + 2 * pads[i] - 1)
        // dils[i] + 1 for i in range(nd))


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from .. import nn
    filter_size = _transpose_filter_size(input, output_size, filter_size,
                                         stride, padding, dilation, 2)
    return _conv(nn.Conv2DTranspose, input, num_filters, filter_size, stride,
                 padding, dilation, groups, param_attr, bias_attr, act, name)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from .. import nn
    filter_size = _transpose_filter_size(input, output_size, filter_size,
                                         stride, padding, dilation, 3)
    return _conv(nn.Conv3DTranspose, input, num_filters, filter_size, stride,
                 padding, dilation, groups, param_attr, bias_attr, act, name)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import deform_conv2d as _dc
    from .. import nn
    C = as_tensor_data(x).shape[1]
    layer = _get_layer(name, lambda: nn.Conv2D(
        C, num_filters, filter_size, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _dc(x, offset, layer.weight, layer.bias, stride, padding,
               dilation, deformable_groups, groups, mask)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import nn
    C = as_tensor_data(x).shape[1]
    num = 1 if mode == "all" else C
    layer = _get_layer(name, lambda: nn.PReLU(
        num_parameters=num, weight_attr=param_attr))
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Power-iteration spectral normalization of a weight tensor
    (ref common.py spectral_norm)."""
    w = as_tensor_data(weight)
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    u = jnp.ones((mat.shape[0],), jnp.float32)
    for _ in range(max(power_iters, 1)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    return wrap(w / sigma)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x W_k y^T + b (ref common.py bilinear_tensor_product)."""
    from .. import nn
    dx = as_tensor_data(x).shape[-1]
    dy = as_tensor_data(y).shape[-1]
    layer = _get_layer(name, lambda: nn.Bilinear(
        dx, dy, size, weight_attr=param_attr, bias_attr=bias_attr))
    return _act(layer(x, y), act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (ref common.py row_conv): y[t] = sum_{i=0..k}
    w[i] * x[t+i], per feature channel."""
    xd = as_tensor_data(input)  # [B, T, D]
    k = future_context_size + 1
    D = xd.shape[-1]
    from ..nn.layer_base import Layer

    class _RowConv(Layer):
        def __init__(self):
            super().__init__()
            self.weight = self.create_parameter([k, D], attr=param_attr)

    # fresh parameters per call, like the reference's per-Program append
    w = _RowConv().weight._data
    pad = jnp.pad(xd, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(pad[:, i:i + xd.shape[1]] * w[i] for i in range(k))
    return _act(wrap(out, stop_gradient=False), act)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (ref loss.py nce). The CUDA
    reference samples negatives to avoid a full-vocab matmul; the TPU MXU
    eats the full matmul, so negatives are drawn but the math is the
    standard NCE logistic objective."""
    from .. import nn
    D = as_tensor_data(input).shape[-1]
    k = num_neg_samples or 10
    layer = _get_layer(name, lambda: nn.Linear(
        D, num_total_classes, weight_attr=param_attr, bias_attr=bias_attr))
    logits = as_tensor_data(layer(input))  # [B, V]
    lab = as_tensor_data(label).reshape(-1).astype(jnp.int32)
    B = logits.shape[0]
    if seed:
        key = jax.random.key(seed)
    else:  # fresh negatives every call via the framework RNG stream
        from ..framework.random import next_key
        key = next_key()
    neg = jax.random.randint(key, (B, k), 0, num_total_classes)
    pos_logit = jnp.take_along_axis(logits, lab[:, None], axis=1)
    neg_logit = jnp.take_along_axis(logits, neg, axis=1)
    loss = -jax.nn.log_sigmoid(pos_logit) - \
        jax.nn.log_sigmoid(-neg_logit).sum(axis=1, keepdims=True)
    return wrap(loss, stop_gradient=False)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op inside a traced program via jax.pure_callback
    (ref common.py py_func — the honest XLA mapping of a host callback)."""
    xs = [as_tensor_data(v) for v in (x if isinstance(x, (list, tuple)) else [x])]
    shape_dtype = jax.ShapeDtypeStruct(
        tuple(as_tensor_data(out).shape), as_tensor_data(out).dtype)
    res = jax.pure_callback(lambda *a: np.asarray(func(*a)), shape_dtype, *xs)
    return wrap(res)


# ---- control flow (ref static/nn/control_flow.py): lax under tracing ----

def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    p = as_tensor_data(pred)
    if _is_tracer(p):
        return jax.lax.cond(jnp.reshape(p, ()), lambda _: true_fn(),
                            lambda _: false_fn(), None)
    return true_fn() if bool(np.asarray(jax.device_get(p))) else false_fn()


def case(pred_fn_pairs, default=None, name=None):
    preds = [as_tensor_data(p) for p, _ in pred_fn_pairs]
    if any(_is_tracer(p) for p in preds):
        # first-true-wins cascade lowered to nested lax.cond (the reference
        # emits a cascade of conditional blocks, control_flow.py case)
        tail = default if default is not None else pred_fn_pairs[-1][1]

        def build(i):
            if i == len(pred_fn_pairs):
                return tail
            p, fn = preds[i], pred_fn_pairs[i][1]
            rest = build(i + 1)
            return lambda: jax.lax.cond(
                jnp.reshape(jnp.asarray(p), ()).astype(bool),
                lambda _: fn(), lambda _: rest(), None)
        return build(0)()
    for p, (pred, fn) in zip(preds, pred_fn_pairs):
        if bool(np.asarray(jax.device_get(p))):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = as_tensor_data(branch_index)
    fns = dict(branch_fns) if isinstance(branch_fns, (list, tuple)) and \
        isinstance(branch_fns[0], (list, tuple)) else branch_fns
    if not isinstance(fns, dict):
        raise TypeError("branch_fns must be a dict or list of (index, fn)")
    keys = sorted(fns)
    fallback = default if default is not None else fns[keys[-1]]
    if _is_tracer(idx):
        # map user keys -> positional branches; unmatched keys hit the
        # trailing fallback branch (reference `default` semantics)
        flat = jnp.reshape(idx, ())
        pos = jnp.full((), len(keys), jnp.int32)
        for j, k in enumerate(keys):
            pos = jnp.where(flat == k, j, pos)
        return jax.lax.switch(pos, [fns[k] for k in keys] + [fallback])
    i = int(np.asarray(jax.device_get(idx)))
    return fns[i]() if i in fns else fallback()


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    vals = loop_vars
    first = as_tensor_data(cond_fn(*vals))
    if _is_tracer(first) or any(_is_tracer(as_tensor_data(v)) for v in vals):
        return jax.lax.while_loop(
            lambda vs: jnp.reshape(as_tensor_data(cond_fn(*vs)), ()),
            lambda vs: tuple(body(*vs)), tuple(vals))
    while bool(np.asarray(jax.device_get(as_tensor_data(cond_fn(*vals))))):
        vals = body(*vals)
        if not isinstance(vals, (list, tuple)):
            vals = (vals,)
    return vals


# sequence ops (dense-padded analogs of the LoD originals — see sequence.py)
from .sequence import (  # noqa: E402,F401
    sequence_softmax, sequence_pool, sequence_first_step, sequence_last_step,
    sequence_reverse, sequence_concat, sequence_slice, sequence_expand,
    sequence_expand_as, sequence_pad, sequence_unpad, sequence_reshape,
    sequence_scatter, sequence_enumerate, sequence_conv, StaticRNN,
)
