"""Tensor-parallel compute/communication overlap + sequence parallelism.

The GSPMD mp schedule (fleet/mp_layers dist_specs) is reference-shaped: two
blocking all-reduces per transformer block with activations fully replicated
across the mp group. This module makes the mp-axis schedule explicit under
`shard_map` so it can be restructured (papers: T3 arXiv:2401.16677 —
fine-grained overlap of compute & collectives; "Optimizing Distributed ML
Communication with Fused Computation-Collective Operations"
arXiv:2305.06942; Megatron-LM sequence parallelism arXiv:2205.05198):

  * sequence parallelism (`FLAGS_sequence_parallel`): activations between TP
    blocks live seq-sharded at 1/mp size; norms/residuals compute on the
    shard. The two per-block `psum`s become a reduce-scatter after each
    RowParallel matmul and an all-gather before each ColumnParallel matmul —
    same wire bytes as the all-reduce pair (ring AR = RS+AG by
    construction), but per-replica activation memory drops by mp;

  * ring-decomposed overlap (`FLAGS_mp_overlap`, requires sequence
    parallelism): the pre-QKV/FFN all-gather splits into mp-1 `ppermute`
    hops with each chunk's GEMM issued as soon as its shard arrives, and the
    RowParallel GEMM emits partial products chunk-by-chunk into a pipelined
    ring reduce-scatter. Each hop's transfer is independent of the GEMM
    consuming the previous chunk, so XLA's latency-hiding scheduler slides
    ICI transfers under MXU work instead of serializing at a collective.

Everything is gated: with both flags OFF nothing here is consulted and the
compiled program is byte-identical to the GSPMD schedule. The explicit
schedule is static per compiled step, so its wire bytes / collective counts
are computed up front (`gpt_step_record`) and recorded per executed step for
`paddle_tpu.profiler.mp_comm_counters()` — the mp-axis sibling of
grad_comm's dp counters.

jax 0.4.x partitioner note: the block `shard_map` binds EVERY mesh axis
manually (full-manual) — partial-manual regions with a live auto axis crash
XLA's SPMD partitioner on `ppermute`/`all_gather` (verified on 0.4.37), and
full-manual is also what makes shard_map's transpose insert the dp psum for
the replicated weight gradients. `resolve_gpt` therefore requires every
mesh axis besides dp/mp to be size 1.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

logger = logging.getLogger(__name__)

_warned = set()


def _warn_once(key, msg):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg)


def _flags():
    from .. import flags as _f
    return _f._FLAGS


def sequence_parallel_requested():
    return bool(_flags().get("FLAGS_sequence_parallel", False))


def mp_overlap_requested():
    return bool(_flags().get("FLAGS_mp_overlap", False))


def mp_backend_requested():
    """The mp-axis comm backend, resolved across FLAGS_comm_backend and the
    legacy flags: None (pure GSPMD, seed path), 'rsag' (sequence-parallel
    layout, whole RS/AG collectives), 'ring' (ppermute decomposition,
    PR 3's overlap), 'fused' (Pallas kernels). Naming mp=ring/fused in
    FLAGS_comm_backend implies the sequence-parallel layout."""
    from . import comm_backend
    req = comm_backend.requested("mp")
    if req is None:
        if not sequence_parallel_requested():
            return None
        return "ring" if mp_overlap_requested() else "rsag"
    if req == "gspmd":
        return "rsag" if sequence_parallel_requested() else None
    return req


def explicit_mp_requested():
    """Whether any flag asks for the explicit (shard_map) mp schedule."""
    return mp_backend_requested() is not None


# ---------------------------------------------------------------------------
# shard-space primitives (called inside a full-manual shard_map; `axis` is
# the bound mp axis name, `n` its static size)


def seq_all_gather(x, axis, n):
    """[B, s, ...] seq-shard -> [B, S, ...] full sequence (one collective)."""
    if n == 1:
        return x
    return lax.all_gather(x, axis, axis=1, tiled=True)


def seq_reduce_scatter(y, axis, n):
    """[B, S, ...] per-device partial -> [B, s, ...] reduced seq-shard."""
    if n == 1:
        return y
    return lax.psum_scatter(y, axis, scatter_dimension=1, tiled=True)


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_ag_gemm(x, w, axis, n):
    """Fused all-gather+GEMM: x [B, s, H] seq-shard, w [H, F_shard] ->
    [B, S, F_shard], decomposed into mp-1 ppermute hops. The GEMM of the
    chunk in hand never depends on the hop fetching the next chunk, so the
    transfer hides behind MXU work (T3-style)."""
    if n == 1:
        return x @ w
    idx = lax.axis_index(axis)
    B, s, _ = x.shape
    out = jnp.zeros((B, n * s, w.shape[1]), x.dtype)
    perm = _ring_perm(n)
    chunk = x
    for t in range(n):
        src = (idx - t) % n  # owner of the chunk in hand
        out = lax.dynamic_update_slice_in_dim(out, chunk @ w, src * s, axis=1)
        if t < n - 1:
            chunk = lax.ppermute(chunk, axis, perm)
    return out


def gemm_ring_rs(y, w, axis, n):
    """Fused GEMM+reduce-scatter: y [B, S, F_shard], w [F_shard, H] ->
    [B, s, H] reduced seq-shard. The accumulator for chunk c rides the ring
    visiting every device once; each device adds its partial GEMM for the
    chunk currently passing through, so partial products stream into the
    collective chunk-by-chunk instead of materializing [B, S, H]."""
    if n == 1:
        return y @ w
    idx = lax.axis_index(axis)
    B, S, F = y.shape
    s = S // n
    perm = _ring_perm(n)
    acc = None
    for t in range(n):
        c = (idx - t - 1) % n  # chunk finishing at device c+t+1
        part = lax.dynamic_slice_in_dim(y, c * s, s, axis=1) @ w
        acc = part if acc is None else acc + part
        if t < n - 1:
            acc = lax.ppermute(acc, axis, perm)
    return acc


def column_parallel(x, w, b, axis, n, backend, meta=None):
    """Seq-sharded input -> full-seq, feature-sharded output (the all-gather
    'before ColumnParallel'). b is the per-device bias shard (or None).
    backend: 'rsag' (whole collectives), 'ring' (ppermute hops), 'fused'
    (Pallas AG+GEMM kernel — meta is its static RingMeta)."""
    if backend == "fused":
        from ..ops.pallas_kernels import fused_collectives as _fc
        out = _fc.fused_ag_gemm(meta, x, w)
    elif backend == "ring":
        out = ring_ag_gemm(x, w, axis, n)
    else:
        out = seq_all_gather(x, axis, n) @ w
    return out if b is None else out + b


def row_parallel(y, w, b, axis, n, backend, meta=None):
    """Full-seq, feature-sharded input -> seq-sharded reduced output (the
    reduce-scatter 'after RowParallel'). b is the FULL bias, added once
    after the cross-device reduction."""
    if backend == "fused":
        from ..ops.pallas_kernels import fused_collectives as _fc
        out = _fc.fused_gemm_rs(meta, y, w)
    elif backend == "ring":
        out = gemm_ring_rs(y, w, axis, n)
    else:
        out = seq_reduce_scatter(y @ w, axis, n)
    return out if b is None else out + b


# ---------------------------------------------------------------------------
# sequence-parallel GPT block (per-device shards; mirrors gpt.gpt_block_fn)


def qkv_head_major_perm(H, nh):
    """Column permutation [3H] taking the logical [3, nh, d] qkv layout to
    head-major [nh, 3, d]: position (h, a, dd) <- logical column (a, h, dd).
    Head-major is what makes a contiguous 1/mp column shard equal the
    q/k/v projections of exactly nh/mp heads; the logical layout interleaves
    head groups across shard boundaries, so a contiguous shard would
    regroup DIFFERENT columns into heads (a different model)."""
    d = H // nh
    a, h, dd = np.meshgrid(np.arange(3), np.arange(nh), np.arange(d),
                           indexing="ij")
    logical = (a * H + h * d + dd).reshape(3, nh, d)
    return logical.transpose(1, 0, 2).reshape(-1)


def to_qkv_head_major(blocks, H, nh):
    """Permute stacked qkv_w [L, H, 3H] / qkv_b [L, 3H] storage to
    head-major. A pure relabeling: with `config.qkv_head_major` set, every
    consumer indexes the permuted positions, so compute is bitwise
    identical to the logical layout."""
    perm = qkv_head_major_perm(H, nh)
    out = dict(blocks)
    out["qkv_w"] = jnp.asarray(blocks["qkv_w"])[..., perm]
    out["qkv_b"] = jnp.asarray(blocks["qkv_b"])[..., perm]
    return out


def sp_block_fn(config, n, axis="mp", backend="rsag", meta=None):
    """Pure (params, x) block on PER-DEVICE shards: x [B, S/mp, H]; matmul
    weights arrive mp-sharded (qkv_w [H, 3H/mp] head-major, out_w [H/mp, H],
    up_w [H, I/mp], down_w [I/mp, H]); norms/biases-of-row replicated.
    Attention runs heads-parallel (nh/mp heads, full sequence) exactly like
    the GSPMD schedule — only the inter-matmul activation layout changes.
    Requires config.qkv_head_major storage (resolve_gpt gates on it).
    backend selects the collective decomposition ('rsag' | 'ring' |
    'fused' — see FLAGS_comm_backend)."""
    from ..models.gpt import ln_fp32, _attention

    nh = config.num_heads
    eps = config.layer_norm_epsilon

    def block(p, x):
        B, s, H = x.shape
        nh_l = nh // n
        d = H // nh
        h1 = ln_fp32(x, p["ln1_g"], p["ln1_b"], eps)
        qkv = column_parallel(h1, p["qkv_w"].astype(x.dtype),
                              p["qkv_b"].astype(x.dtype), axis, n, backend,
                              meta)
        S = qkv.shape[1]
        qkv4 = qkv.reshape(B, S, nh_l, 3, d)  # head-major local columns
        q, k, v = qkv4[..., 0, :], qkv4[..., 1, :], qkv4[..., 2, :]
        ctx = _attention(q, k, v, config.use_flash,
                         block_q=getattr(config, "flash_block_q", 256),
                         block_k=getattr(config, "flash_block_k", 256))
        from jax.ad_checkpoint import checkpoint_name
        ctx = checkpoint_name(ctx, "attn_ctx")
        attn_out = row_parallel(ctx.reshape(B, S, nh_l * d),
                                p["out_w"].astype(x.dtype),
                                p["out_b"].astype(x.dtype), axis, n, backend,
                                meta)
        x = x + attn_out
        h2 = ln_fp32(x, p["ln2_g"], p["ln2_b"], eps)
        up = column_parallel(h2, p["up_w"].astype(x.dtype),
                             p["up_b"].astype(x.dtype), axis, n, backend,
                             meta)
        up = jax.nn.gelu(up, approximate=True)
        down = row_parallel(up, p["down_w"].astype(x.dtype),
                            p["down_b"].astype(x.dtype), axis, n, backend,
                            meta)
        return x + down

    return block


SP_BLOCK_PARAM_SPECS = {
    "ln1_g": P(None), "ln1_b": P(None),
    "qkv_w": P(None, "mp"), "qkv_b": P("mp"),
    "out_w": P("mp", None), "out_b": P(None),
    "ln2_g": P(None), "ln2_b": P(None),
    "up_w": P(None, "mp"), "up_b": P("mp"),
    "down_w": P("mp", None), "down_b": P(None),
}


def sp_activation_spec(batch_axis="dp"):
    """Inter-block activation layout: batch over dp, sequence over mp."""
    return P(batch_axis, "mp", None)


def make_sp_block(config, mesh, cfg):
    """shard_map-wrapped sequence-parallel block for the gpt_hidden layer
    scan: (layer_params, x[B,S,H] logical) -> x. Full-manual over every mesh
    axis (see module docstring for why partial-manual is not an option on
    jax 0.4.x); axes other than dp/mp are size-1 by `resolve_gpt` gating."""
    from .env import shard_map_compat
    block = sp_block_fn(config, cfg.n, axis=cfg.axis, backend=cfg.backend,
                        meta=cfg.kernel_meta(mesh))
    x_spec = sp_activation_spec(cfg.batch_axis)
    return shard_map_compat(
        block, mesh,
        in_specs=(dict(SP_BLOCK_PARAM_SPECS), x_spec),
        out_specs=x_spec)


# ---------------------------------------------------------------------------
# gating


@dataclass
class SPConfig:
    axis: str          # mp axis name
    n: int             # mp size
    backend: str       # 'rsag' | 'ring' | 'fused'
    batch_axis: str = "dp"     # None on a mesh without a dp axis

    @property
    def overlap(self):
        """PR 3 compatibility: whether the ring (ppermute) decomposition
        runs. The fused backend overlaps too, but in-kernel."""
        return self.backend == "ring"

    def kernel_meta(self, mesh):
        if self.backend != "fused":
            return None
        from ..ops.pallas_kernels import fused_collectives as _fc
        return _fc.meta_for(mesh, self.axis)


def resolve_gpt(config, mesh, batch=None, seq=None):
    """Decide whether the explicit sequence-parallel schedule applies to a
    gpt_hybrid step. Returns SPConfig or None (None = GSPMD schedule,
    byte-identical to the seed). Every bail warns once with the reason AND
    the exact flag setting that would fix it — the fallback rules
    documented in README ("Communication backends")."""
    backend = mp_backend_requested()
    if backend is None:
        if mp_overlap_requested():
            _warn_once("overlap-needs-sp",
                       "FLAGS_mp_overlap requires FLAGS_sequence_parallel; "
                       "ignoring (GSPMD schedule kept) — set "
                       "FLAGS_sequence_parallel=True (or "
                       "FLAGS_comm_backend='mp=ring') to enable the "
                       "explicit schedule")
        return None
    if mesh is None:
        return None
    mp = mesh.shape.get("mp", 1)
    if mp <= 1:
        return None

    def bail(key, msg):
        _warn_once(key, msg + " — falling back to the GSPMD mp schedule")
        return None

    allowed = ("dp", "mp")
    from . import comm_backend as _cb
    if _cb.pp_explicit_requested():
        # the explicit pipeline (comm_backend.resolve_pp) binds the whole
        # mesh manually and runs the per-shard sp block INSIDE its region —
        # an active pp axis composes instead of blocking the sp schedule
        allowed = ("dp", "mp", "pp")
    extra = [a for a in mesh.axis_names
             if a not in allowed and mesh.shape.get(a, 1) > 1]
    if extra:
        return bail(("axes", tuple(extra)),
                    f"sequence parallelism binds the whole mesh manually; "
                    f"axes {extra} must be size 1 (set them to 1 in "
                    f"create_hybrid_mesh, set FLAGS_comm_backend='pp=ring' "
                    f"to compose an active pp axis, or drop the explicit "
                    f"schedule with FLAGS_comm_backend='mp=gspmd')")
    H = config.hidden_size
    if H % mp or config.num_heads % mp or (config.ffn_mult * H) % mp:
        return bail(("dims", H, config.num_heads, mp),
                    f"hidden {H}/heads {config.num_heads}/ffn not divisible "
                    f"by mp={mp} (choose an mp degree dividing all three)")
    if not getattr(config, "qkv_head_major", False):
        # the sp block reads a contiguous qkv column shard as nh/mp whole
        # heads, which is only true of head-major storage; HybridTrainStep
        # permutes at init — a caller handing logical-layout params would
        # silently compute a different model
        return bail("qkv-layout",
                    "sequence parallelism needs head-major qkv storage "
                    "(config.qkv_head_major; HybridTrainStep sets it up)")
    if seq is not None and seq % mp:
        return bail(("seq", seq, mp),
                    f"sequence {seq} not divisible by mp={mp} (pad the "
                    f"sequence or lower the mp degree)")
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    dp = mesh.shape.get("dp", 1)
    if batch is not None and dp > 1 and batch % dp:
        return bail(("batch", batch, dp),
                    f"batch {batch} not divisible by dp={dp} (adjust the "
                    f"global batch or the dp degree)")
    if backend == "fused":
        from ..ops.pallas_kernels import fused_collectives as _fc
        # lane dims the Mosaic kernels see: hidden (chunk/GEMM lane), the
        # qkv and ffn weight-shard widths
        ok, why = _fc.supported(
            mesh, shapes=(H, 3 * H // mp, config.ffn_mult * H // mp),
            why="mp axis")
        if not ok:
            _warn_once(("fused-mp", tuple(mesh.axis_names)),
                       f"fused mp backend unavailable: {why} — falling back "
                       f"to FLAGS_comm_backend='mp=ring'")
            backend = "ring"
    if backend == "ring" and jax.default_backend() == "cpu" and \
            jnp.dtype(config.compute_dtype or "float32") == jnp.bfloat16:
        # same XLA CPU abort as the bf16 ppermute pipeline (gpt_hidden's
        # pp>1 guard); plain RS/AG sequence parallelism is unaffected
        _warn_once("cpu-bf16-overlap",
                   "mp overlap uses ppermute, which the XLA CPU backend "
                   "cannot partition in bf16 — running sequence parallelism "
                   "without overlap on CPU (use compute_dtype='float32' on "
                   "CPU, or FLAGS_comm_backend='mp=fused' on a single-axis "
                   "mesh)")
        backend = "rsag"
    return SPConfig(axis="mp", n=int(mp), backend=backend,
                    batch_axis=batch_axis)


# ---------------------------------------------------------------------------
# serving entry points: the tensor-parallel serving engine's mp rung
# (serving/mp_forward.py) resolves its collective schedule here, next to
# the training schedule it mirrors


@dataclass(frozen=True)
class ServingMPConfig:
    """Static mp configuration of a serving engine (hashable — it keys the
    engine's memoized executable builders). ``backend`` names the serving
    RUNG: 'gspmd' (whole all-gather collectives — the schedule the
    partitioner would emit for a gather-only program), 'ring' (ppermute
    decomposition) or 'fused' (Pallas in-kernel rings). All three rungs
    run the SAME gather-only math, so engine output is bitwise identical
    across rungs AND to the single-chip engine."""
    axis: str
    n: int
    backend: str       # 'gspmd' | 'ring' | 'fused'
    shard_vocab: bool  # lm head + logits AG sharded over vocab (V % n == 0)

    def kernel_meta(self, mesh):
        if self.backend != "fused":
            return None
        from ..ops.pallas_kernels import fused_collectives as _fc
        return _fc.meta_for(mesh, self.axis)


def resolve_serving(config, mesh, backend=None):
    """Resolve the serving engine's mp schedule for ``mesh`` (a 1-D 'mp'
    mesh; other axes must be size 1). Returns ``ServingMPConfig`` or None
    when mp <= 1. Unlike ``resolve_gpt`` the serving schedule is
    GATHER-ONLY — every GEMM shards its OUTPUT dim and keeps the full
    contraction, so no cross-chip reduction ever happens and the engine's
    bitwise-parity contract with single-chip ``generate_from_params``
    survives sharding. Hard config errors raise (a serving deploy must not
    silently change layout); backend ineligibility degrades one rung with
    a warning naming the fix, like the training resolver."""
    if mesh is None:
        return None
    mp = int(mesh.shape.get("mp", 1))
    if mp <= 1:
        return None
    extra = [a for a in mesh.axis_names
             if a != "mp" and mesh.shape.get(a, 1) > 1]
    if extra:
        raise ValueError(
            f"serving mp mesh must be 1-D over 'mp'; axes {extra} have "
            f"size > 1 (build the replica mesh with "
            f"dist_env.create_single_axis_mesh('mp', n) or "
            f"serving.mp_replica_meshes)")
    H = config.hidden_size
    nh = config.num_heads
    I = config.ffn_mult * H
    if H % mp or nh % mp or I % mp:
        raise ValueError(
            f"serving mp={mp} must divide hidden {H}, heads {nh} and ffn "
            f"{I} (choose an mp degree dividing all three)")
    if backend is None:
        from . import comm_backend
        backend = comm_backend.serving_requested() or "gspmd"
    if backend == "fused":
        from ..ops.pallas_kernels import fused_collectives as _fc
        ok, why = _fc.supported(
            mesh, shapes=(H, 3 * H // mp, I // mp, H // mp),
            why="serving mp")
        if not ok:
            _warn_once(("fused-serving", tuple(mesh.axis_names)),
                       f"fused serving backend unavailable: {why} — "
                       f"falling back to FLAGS_comm_backend='mp=ring'")
            backend = "ring"
    if backend == "ring" and jax.default_backend() == "cpu" and \
            jnp.dtype(config.compute_dtype or "float32") == jnp.bfloat16:
        _warn_once("cpu-bf16-serving-ring",
                   "serving ring rung uses ppermute, which the XLA CPU "
                   "backend cannot partition in bf16 — using whole "
                   "collectives (gspmd rung) on CPU")
        backend = "gspmd"
    shard_vocab = config.vocab_size % mp == 0
    if not shard_vocab:
        _warn_once(("serving-vocab", config.vocab_size, mp),
                   f"vocab {config.vocab_size} not divisible by serving "
                   f"mp={mp}: the embedding stays feature-sharded but the "
                   f"lm head and logits stay replicated (pad the vocab to "
                   f"a multiple of mp to shard them)")
    return ServingMPConfig(axis="mp", n=mp, backend=str(backend),
                           shard_vocab=shard_vocab)


def serving_step_record(config, cfg: ServingMPConfig, B, T):
    """Static per-device mp wire ledger of ONE fused serving dispatch at
    window shape [B, T] (decode: [slots, 1]; prefill chunk: [1, rung]).
    Gather-only schedule — per block an AG of the attention context
    (contraction input of the out projection), the out projection's output
    blocks, the FFN activation and the down projection's output blocks,
    plus the embedding AG and (vocab-sharded) the logits AG. Recorded per
    executed dispatch into the SAME counters as the training schedule
    (``profiler.mp_comm_counters``)."""
    n = cfg.n
    item = jnp.dtype(config.compute_dtype or "float32").itemsize
    H = config.hidden_size
    I = config.ffn_mult * H
    L = config.num_layers
    R = B * T

    def ag(F, isz=item):
        # ring all-gather: each device sends its 1/n block to n-1 peers
        return R * F * isz * (n - 1) // n

    rec = MpStepRecord()
    rec.backend = cfg.backend
    total = ag(H) + L * (ag(H) + ag(H) + ag(I) + ag(H))
    colls = 1 + 4 * L
    if cfg.shard_vocab:
        # logits exist only at each slot's LAST position ([B, V] fp32),
        # not per window token — a chunk-prefill dispatch still gathers
        # one row per slot
        total += B * config.vocab_size * 4 * (n - 1) // n
        colls += 1
    rec.ag_bytes = total
    rec.collectives = colls
    rec.bytes_by_kind = {"all_gather": total}
    if cfg.backend == "ring":
        rec.ppermute_hops = colls * (n - 1)
    elif cfg.backend == "fused":
        rec.fused_dispatches = colls
    rec.activation_bytes = R * H * item
    return rec


# ---------------------------------------------------------------------------
# mp_layers routing (Column/RowParallelLinear explicit overlap path)


def layer_schedule(mesh):
    """What the mp layers should do under the current flags/mesh:
    'gspmd' — seed behavior; 'seq' — GSPMD with seq-sharded constraints
    (RS+AG emitted by the partitioner); 'explicit' — route the matmul
    through the shard_map ring kernels; 'fused' — route it through the
    Pallas fused GEMM+collective kernels. Inside an existing SPMD manual
    region (grad_comm's dp step, the pipeline) shard_map cannot nest, so
    the explicit paths degrade to 'seq' there."""
    if mesh is None or mesh.shape.get("mp", 1) <= 1:
        return "gspmd"
    backend = mp_backend_requested()
    if backend is None:
        return "gspmd"
    if backend == "rsag":
        return "seq"
    from .collective import _in_spmd
    if any(_in_spmd(a) for a in mesh.axis_names):
        return "seq"
    extra = [a for a in mesh.axis_names
             if a not in ("dp", "mp") and mesh.shape.get(a, 1) > 1]
    if extra:
        return "seq"
    if backend == "fused":
        from ..ops.pallas_kernels import fused_collectives as _fc
        ok, why = _fc.supported(mesh, shapes=(), why="mp layers")
        if not ok:
            _warn_once(("fused-layers", tuple(mesh.axis_names)),
                       f"fused mp backend unavailable for the mp layers: "
                       f"{why} — falling back to "
                       f"FLAGS_comm_backend='mp=ring'")
            return "explicit"
        return "fused"
    return "explicit"


def layer_shapes_ok(x, w, mesh, column):
    """Whether the explicit ring kernels can take this Column/Row matmul:
    3D activations with mp-divisible sequence and weight shard dims (and a
    dp-divisible batch when dp is active)."""
    if getattr(x, "ndim", 0) != 3:
        return False
    mp = mesh.shape.get("mp", 1)
    dp = mesh.shape.get("dp", 1)
    B, S, _ = x.shape
    if S % mp or (dp > 1 and B % dp):
        return False
    shard_dim = w.shape[1] if column else w.shape[0]
    return shard_dim % mp == 0


def _layer_backend(mesh):
    """Backend + kernel meta for the mp-layer wrappers ('explicit' mode ->
    ring, 'fused' mode -> Pallas kernels)."""
    if layer_schedule(mesh) == "fused":
        from ..ops.pallas_kernels import fused_collectives as _fc
        return "fused", _fc.meta_for(mesh, "mp")
    return "ring", None


def column_linear(x, w, b, mesh, gather_output):
    """Logical-shape ColumnParallelLinear forward on the explicit schedule:
    x [B,S,H] seq-sharded between blocks, w [H, F] mp-sharded on F. The
    bias (mp-sharded on F) is added on the logical output — elementwise, no
    extra collective."""
    from .env import shard_map_compat
    mp = int(mesh.shape.get("mp", 1))
    batch_axis = "dp" if mesh.shape.get("dp", 1) > 1 else None
    x_spec = P(batch_axis, "mp", None)
    backend, meta = _layer_backend(mesh)

    def f(xs, ws):
        return column_parallel(xs, ws, None, "mp", mp, backend, meta)

    mapped = shard_map_compat(
        f, mesh, in_specs=(x_spec, P(None, "mp")),
        out_specs=P(batch_axis, None, "mp"))
    out = mapped(x, w)
    if b is not None:
        out = out + b
    if gather_output:
        return jax.lax.with_sharding_constraint(
            out, jax.sharding.NamedSharding(mesh, P(batch_axis, None, None)))
    return out


def row_linear(x, w, b, mesh):
    """Logical-shape RowParallelLinear forward on the explicit schedule:
    x [B,S,F] mp-sharded on F, w [F, H] mp-sharded on F; output seq-sharded
    [B,S,H] (the next block's norms/residuals run on the shard). The full
    bias is added once on the logical reduced output."""
    from .env import shard_map_compat
    mp = int(mesh.shape.get("mp", 1))
    batch_axis = "dp" if mesh.shape.get("dp", 1) > 1 else None
    backend, meta = _layer_backend(mesh)

    def f(xs, ws):
        return row_parallel(xs, ws, None, "mp", mp, backend, meta)

    mapped = shard_map_compat(
        f, mesh, in_specs=(P(batch_axis, None, "mp"), P("mp", None)),
        out_specs=P(batch_axis, "mp", None))
    out = mapped(x, w)
    return out if b is None else out + b


# ---------------------------------------------------------------------------
# static schedule ledger + per-step counters (profiler.mp_comm_counters)


@dataclass
class MpStepRecord:
    """Per-device mp-axis wire traffic of one executed step's forward
    schedule (the backward mirrors it: the transpose of a seq all-gather is
    a seq reduce-scatter and vice versa)."""
    collectives: int = 0          # RS/AG issued (ring counts its hop group)
    ppermute_hops: int = 0        # individual ring hops (ring backend only)
    fused_dispatches: int = 0     # Pallas kernel launches (fused backend)
    backend: str = "gspmd"        # the mp-axis backend that produced this
    rs_bytes: int = 0
    ag_bytes: int = 0
    bytes_by_kind: dict = field(default_factory=dict)
    activation_bytes: int = 0     # inter-block activation residency/device


def gpt_step_record(config, cfg: SPConfig, batch, seq):
    """Ledger of the explicit schedule for one gpt_hybrid step: per block
    an AG before QKV, an RS after the attention output projection, an AG
    before the FFN up-projection, an RS after the down-projection. Under
    the fused backend the same four positions are Pallas kernel launches
    (fused_dispatches) moving the same wire bytes with ZERO XLA-level
    ppermute hops and no HBM-materialized gather buffer."""
    n = cfg.n
    item = jnp.dtype(config.compute_dtype or "float32").itemsize
    s = seq // n
    chunk = batch * s * config.hidden_size * item   # one seq-chunk
    per_coll = (n - 1) * chunk                      # RS and AG move the same
    L = config.num_layers
    rec = MpStepRecord()
    rec.rs_bytes = 2 * L * per_coll
    rec.ag_bytes = 2 * L * per_coll
    rec.collectives = 4 * L
    rec.backend = cfg.backend
    if cfg.backend == "ring":
        rec.ppermute_hops = 4 * L * (n - 1)
    elif cfg.backend == "fused":
        rec.fused_dispatches = 4 * L
    rec.bytes_by_kind = {"reduce_scatter": rec.rs_bytes,
                         "all_gather": rec.ag_bytes}
    rec.activation_bytes = chunk
    return rec


def gspmd_baseline_record(config, mp, batch, seq):
    """What the reference GSPMD schedule moves per step (two ring
    all-reduces of the full [B,S,H] activation per block) — the comparison
    row for tools_tp_smoke's ladder."""
    item = jnp.dtype(config.compute_dtype or "float32").itemsize
    full = batch * seq * config.hidden_size * item
    per_ar = 2 * (mp - 1) * full // mp
    L = config.num_layers
    rec = MpStepRecord()
    rec.collectives = 2 * L
    rec.bytes_by_kind = {"all_reduce": 2 * L * per_ar}
    rec.rs_bytes = 0
    rec.ag_bytes = 0
    rec.activation_bytes = full
    return rec


_lock = threading.Lock()


def _zero_counters():
    return {"steps": 0, "collectives": 0, "ppermute_hops": 0,
            "fused_dispatches": 0, "backend": {},
            "rs_bytes": 0, "ag_bytes": 0, "bytes_by_kind": {},
            "activation_bytes": 0}


_counters = _zero_counters()


def record_step(rec: MpStepRecord | None):
    if rec is None:
        return
    with _lock:
        _counters["steps"] += 1
        _counters["collectives"] += rec.collectives
        _counters["ppermute_hops"] += rec.ppermute_hops
        _counters["fused_dispatches"] += rec.fused_dispatches
        _counters["backend"]["mp"] = rec.backend
        _counters["rs_bytes"] += rec.rs_bytes
        _counters["ag_bytes"] += rec.ag_bytes
        _counters["activation_bytes"] = rec.activation_bytes
        for k, v in rec.bytes_by_kind.items():
            d = _counters["bytes_by_kind"]
            d[k] = d.get(k, 0) + v


def mp_counters():
    with _lock:
        out = dict(_counters)
        out["bytes_by_kind"] = dict(out["bytes_by_kind"])
        out["backend"] = dict(out["backend"])
    out["wire_bytes"] = sum(out["bytes_by_kind"].values())
    return out


def reset_mp_counters():
    global _counters
    with _lock:
        _counters = _zero_counters()
