"""Distributed environment: device mesh management.

Re-design of the reference's process-group world (ref: python/paddle/
distributed/parallel.py, collective.py). The TPU-native model is
single-controller SPMD: one Python process drives all chips through a
`jax.sharding.Mesh`; "ranks" are mesh coordinates, "process groups" are named
mesh axes, and NCCL communicators are replaced by XLA collectives over ICI.

Multi-host TPU pods: call `init_parallel_env()` which routes to
`jax.distributed.initialize()` when TPU pod env vars are present; jax then
presents every chip in the pod in `jax.devices()` and the same single-
controller code scales out (DCN handled by the runtime).
"""
from __future__ import annotations

import os

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

_global_mesh: Mesh | None = None
_initialized = False

# canonical hybrid-parallel axis order, outermost first. mp innermost so
# tensor-parallel collectives ride neighboring ICI links; ep next-innermost
# so the MoE all_to_all stays on near links too
HYBRID_AXES = ("pp", "dp", "sharding", "sp", "ep", "mp")


def init_parallel_env():
    """ref: paddle.distributed.init_parallel_env."""
    global _initialized
    if _initialized:
        return
    if "TPU_WORKER_HOSTNAMES" in os.environ or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ:
        try:
            jax.distributed.initialize()
        except Exception:
            pass
    _initialized = True


def world_size():
    return jax.device_count()


get_world_size = world_size


def get_rank(group=None):
    return jax.process_index()


def device_count():
    return jax.local_device_count()


def is_initialized():
    return _initialized


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Mesh | None:
    return _global_mesh


def create_hybrid_mesh(dp=1, mp=1, pp=1, sharding=1, sp=1, ep=1,
                       devices=None):
    """Build the hybrid-parallel mesh. Degrees must multiply to device count
    (a trailing dp fill-in is applied when dp == -1)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    known = mp * pp * sharding * sp * ep
    if dp == -1:
        assert n % known == 0, f"{n} devices not divisible by {known}"
        dp = n // known
    total = dp * known
    assert total <= n, (f"hybrid degrees dp{dp}×sharding{sharding}×pp{pp}×sp{sp}"
                        f"×mp{mp}×ep{ep}={total} > {n} devices")
    devices = list(devices)[:total]  # sub-mesh when degrees underfill the slice
    shape = dict(zip(HYBRID_AXES, (pp, dp, sharding, sp, ep, mp)))
    arr = np.array(devices).reshape(tuple(shape[a] for a in HYBRID_AXES))
    mesh = Mesh(arr, HYBRID_AXES)
    set_mesh(mesh)
    return mesh


def replicated_sharding(mesh=None):
    mesh = mesh or _global_mesh
    return NamedSharding(mesh, PartitionSpec())


class ParallelEnv:
    """ref: paddle.distributed.ParallelEnv (legacy accessor)."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0
