"""Distributed environment: device mesh management.

Re-design of the reference's process-group world (ref: python/paddle/
distributed/parallel.py, collective.py). The TPU-native model is
single-controller SPMD: one Python process drives all chips through a
`jax.sharding.Mesh`; "ranks" are mesh coordinates, "process groups" are named
mesh axes, and NCCL communicators are replaced by XLA collectives over ICI.

Multi-host TPU pods: call `init_parallel_env()` which routes to
`jax.distributed.initialize()` when TPU pod env vars are present; jax then
presents every chip in the pod in `jax.devices()` and the same single-
controller code scales out (DCN handled by the runtime).
"""
from __future__ import annotations

import os

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

_global_mesh: Mesh | None = None
_initialized = False

# canonical hybrid-parallel axis order, outermost first. mp innermost so
# tensor-parallel collectives ride neighboring ICI links; ep next-innermost
# so the MoE all_to_all stays on near links too
HYBRID_AXES = ("pp", "dp", "sharding", "sp", "ep", "mp")


def init_parallel_env():
    """ref: paddle.distributed.init_parallel_env."""
    global _initialized
    if _initialized:
        return
    if "TPU_WORKER_HOSTNAMES" in os.environ or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ:
        try:
            jax.distributed.initialize()
        except Exception:
            pass
    _initialized = True


def world_size():
    return jax.device_count()


get_world_size = world_size


def get_rank(group=None):
    return jax.process_index()


def device_count():
    return jax.local_device_count()


def is_initialized():
    return _initialized


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Mesh | None:
    return _global_mesh


def create_hybrid_mesh(dp=1, mp=1, pp=1, sharding=1, sp=1, ep=1,
                       devices=None):
    """Build the hybrid-parallel mesh. Degrees must multiply to device count
    (a trailing dp fill-in is applied when dp == -1)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    known = mp * pp * sharding * sp * ep
    if dp == -1:
        assert n % known == 0, f"{n} devices not divisible by {known}"
        dp = n // known
    total = dp * known
    assert total <= n, (f"hybrid degrees dp{dp}×sharding{sharding}×pp{pp}×sp{sp}"
                        f"×mp{mp}×ep{ep}={total} > {n} devices")
    devices = list(devices)[:total]  # sub-mesh when degrees underfill the slice
    shape = dict(zip(HYBRID_AXES, (pp, dp, sharding, sp, ep, mp)))
    arr = np.array(devices).reshape(tuple(shape[a] for a in HYBRID_AXES))
    mesh = Mesh(arr, HYBRID_AXES)
    set_mesh(mesh)
    return mesh


def create_single_axis_mesh(axis, n=None, devices=None):
    """Mesh with exactly ONE named axis (e.g. ('mp',) or ('dp',)) — the
    layout interpret-mode fused GEMM+collective kernels require (jax<0.5's
    remote-DMA discharge rule supports a single named axis; see
    comm_backend.fused_mesh_ok). On a real TPU create_hybrid_mesh works
    for the fused backend too."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices) if n is None else int(n)
    assert n <= len(devices), (f"create_single_axis_mesh({axis!r}, {n}) "
                               f"needs {n} devices, only "
                               f"{len(devices)} available")
    mesh = Mesh(np.array(devices[:n]), (axis,))
    set_mesh(mesh)
    return mesh


def replicated_sharding(mesh=None):
    mesh = mesh or _global_mesh
    return NamedSharding(mesh, PartitionSpec())


def axis_size(name):
    """Static size of a bound mesh axis inside an SPMD region. jax<0.5 has
    no `lax.axis_size`; `psum` of a literal 1 folds to the size constant."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(name)
    return lax.psum(1, name)


def shard_map_compat(f, mesh, in_specs, out_specs, axis_names=None,
                     check_rep=False):
    """`jax.shard_map(..., axis_names=...)` portability shim: jax<0.5 only
    has jax.experimental.shard_map, whose partial-manual knob is the
    complement set `auto=` instead of `axis_names=`."""
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        # callers rely on disabled replication checking (e.g. pipeline
        # stages return per-device garbage under out_specs=P()); forward it
        # under whichever name this jax spells it
        try:
            import inspect
            sig = inspect.signature(jax.shard_map).parameters
            for flag in ("check_rep", "check_vma"):
                if flag in sig:
                    kw[flag] = check_rep
                    break
        except (TypeError, ValueError):
            pass
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = auto
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_rep, **kw)


class ParallelEnv:
    """ref: paddle.distributed.ParallelEnv (legacy accessor)."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0
