"""Reshard-on-load: topology-elastic checkpoint restoration.

A checkpoint written under weight-update sharding (grad_comm) stores every
optimizer slot — and a mid-window gradient accumulator — in the packed
``(n, cols)`` flat layout of the PRODUCING dp axis (arXiv:2004.13336's
weight-update-sharding layout, ``cols = ceil(size / n)`` with zero pad at
the tail). That layout is a pure function of the parameter shape and the
axis size, so a checkpoint from one mesh maps onto any other: strip the
source padding, re-pad for the destination axis, done — bucket plans are
re-derived by the destination step from its own ``(params, n)`` pair, so
no plan state needs to travel.

This module is the HOST side of that story and is deliberately
numpy-only (no jax import): every leaf is resharded independently —
``(n_src, cols_src) → flat[:size] → (n_dst, cols_dst)`` — so the full
fp32 optimizer state never materializes in one buffer; the destination
step then ``device_put``s each leaf straight to its packed dp-sharded
placement exactly like a same-topology restore.

The second job here is DIAGNOSIS: ``TrainStep.state_dict()`` stamps a
topology record (mesh axis sizes, dp size, wus/accum flags, bucket-plan
fingerprint — see ``TrainStep.topology()``), and a load that cannot be
resharded raises :class:`TopologyMismatchError` NAMING the differing
fields (param names/shapes, accumulate window position, axis sizes)
instead of failing deep inside a reshape.
"""
from __future__ import annotations

import threading

import numpy as np


class TopologyMismatchError(RuntimeError):
    """A checkpoint's topology/layout is incompatible with the restoring
    step in a way reshard-on-load cannot (or was told not to) fix. The
    message names the differing fields."""


# -- counters (observability "elastic" family) -------------------------------

_lock = threading.Lock()


def _zero_counters():
    return {"resharded_loads": 0, "resharded_leaves": 0, "rejected_loads": 0}


_counters = _zero_counters()


def reshard_counters():
    with _lock:
        return dict(_counters)


def reset_reshard_counters():
    global _counters
    with _lock:
        _counters = _zero_counters()


def _count(key, n=1):
    with _lock:
        _counters[key] += n


def note_leaf_reshard(n=1):
    """Bump the leaf counter from external reshard sites (grad_comm's
    pack path reshards foreign-packed leaves on the first compile)."""
    _count("resharded_leaves", n)


def note_load(n_leaves):
    """One reshard-on-load event moving ``n_leaves`` leaves."""
    _count("resharded_loads")
    _count("resharded_leaves", int(n_leaves))


def note_rejected():
    """One refused load (strict mode / unreshardable layout) — every
    ``TopologyMismatchError`` raise site counts here so the elastic
    family's ``rejected_loads`` matches what fleets actually see."""
    _count("rejected_loads")


# -- packed-layout geometry --------------------------------------------------


def _size(pshape):
    return int(np.prod(pshape)) if len(pshape) else 1


def packed_shape(pshape, n):
    """The packed ``(n, cols)`` shape of a param of ``pshape``."""
    return (int(n), -(-_size(pshape) // int(n)))


def packed_n(shape, pshape):
    """The axis size ``m`` when ``shape`` is a CONSISTENT packed layout
    ``(m, ceil(size/m))`` of a param of ``pshape`` — and not the param
    shape itself — else None. This is how a packed leaf from a foreign
    topology is recognized when no metadata travelled with it."""
    shape = tuple(int(s) for s in shape)
    pshape = tuple(int(s) for s in pshape)
    if shape == pshape or len(shape) != 2:
        return None
    m, cols = shape
    if m >= 1 and cols == -(-_size(pshape) // m):
        return m
    return None


def reshard_leaf(v, pshape, n_dst, where="leaf"):
    """One leaf → the destination layout, in numpy on the host.

    Accepts the param shape or the packed layout of ANY axis size;
    returns ``(leaf, resharded)`` where the leaf is param-shaped when
    ``n_dst`` is None and packed ``(n_dst, cols_dst)`` otherwise. A leaf
    already in the destination layout passes through UNTOUCHED (object
    identity — same-topology restores stay byte-identical). Scalars pass
    through. Raises :class:`TopologyMismatchError` naming ``where`` when
    the leaf fits no known layout of ``pshape``."""
    shape = tuple(int(s) for s in np.shape(v))
    pshape = tuple(int(s) for s in pshape)
    dst = packed_shape(pshape, n_dst) if n_dst else pshape
    if shape == dst:
        return v, False
    size = _size(pshape)
    if shape == pshape:  # incl. scalar params: () packs to (n, 1)
        flat = np.asarray(v).reshape(-1)
    else:
        m = packed_n(shape, pshape)
        if m is None:
            _count("rejected_loads")
            raise TopologyMismatchError(
                f"{where}: shape {shape} is neither the param shape "
                f"{pshape} nor a packed (n, ceil({size}/n)) layout — "
                f"this checkpoint was produced by a different model")
        # strip the SOURCE axis's tail padding before re-packing
        flat = np.asarray(v).reshape(-1)[:size]
    if n_dst is None:
        return flat.reshape(pshape), True
    n, cols = dst
    pad = n * cols - size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    return flat.reshape(n, cols), True


def reshard_opt_state(state, pshapes, n_dst):
    """Optimizer ``{"step", "slots"}`` → the destination layout, leaf by
    leaf (streamed — one param's slot in flight at a time). ``pshapes``
    maps param name → shape; ``n_dst`` is the destination packing axis
    size (None = param-shaped slots, the replicated/GSPMD schedule).
    Returns ``(state, n_leaves_resharded)``."""
    slots, moved = {}, 0
    for name, sl in state["slots"].items():
        out = {}
        for k, v in sl.items():
            out[k], did = reshard_leaf(v, pshapes[name], n_dst,
                                       where=f"slot {name}.{k}")
            moved += bool(did)
        slots[name] = out
    return {"step": state["step"], "slots": slots}, moved


def reshard_accum(gacc, pshapes, n_dst):
    """Gradient accumulator → destination layout; same contract as
    :func:`reshard_opt_state`."""
    out, moved = {}, 0
    for name, v in gacc.items():
        out[name], did = reshard_leaf(v, pshapes[name], n_dst,
                                      where=f"grad_accum {name}")
        moved += bool(did)
    return out, moved


# -- diagnosis ---------------------------------------------------------------

_IGNORED_FIELDS = ("format", "resolved")


def diff_topology(src, dst):
    """Named field-by-field difference of two topology records:
    ``[(field, src_value, dst_value), ...]``."""
    src, dst = dict(src or {}), dict(dst or {})
    fields = sorted(set(src) | set(dst))
    return [(f, src.get(f), dst.get(f)) for f in fields
            if f not in _IGNORED_FIELDS and src.get(f) != dst.get(f)]


def describe_diff(diffs):
    return "; ".join(f"{f}: checkpoint={s!r} vs step={d!r}"
                     for f, s, d in diffs)


def check_params(src_params, dst_params, max_named=6):
    """Raise :class:`TopologyMismatchError` naming missing/extra params
    and per-param shape/dtype differences when a checkpoint's parameter
    tree does not match the restoring step's — the diagnosis that
    replaces the opaque downstream reshape error for a WRONG-MODEL load.
    Param leaves are host or device arrays; only names/shapes/dtypes are
    read."""
    if src_params is None:
        return
    bad = []
    src_names, dst_names = set(src_params), set(dst_params)
    for n in sorted(src_names - dst_names):
        bad.append(f"param {n!r}: only in checkpoint")
    for n in sorted(dst_names - src_names):
        bad.append(f"param {n!r}: only in step")
    for n in sorted(src_names & dst_names):
        s, d = src_params[n], dst_params[n]
        if tuple(np.shape(s)) != tuple(np.shape(d)):
            bad.append(f"param {n!r}: shape {tuple(np.shape(s))} "
                       f"(checkpoint) vs {tuple(np.shape(d))} (step)")
        elif hasattr(s, "dtype") and hasattr(d, "dtype") and \
                np.dtype(s.dtype) != np.dtype(d.dtype):
            bad.append(f"param {n!r}: dtype {np.dtype(s.dtype)} "
                       f"(checkpoint) vs {np.dtype(d.dtype)} (step)")
    if bad:
        _count("rejected_loads")
        extra = f" (+{len(bad) - max_named} more)" if len(bad) > max_named \
            else ""
        raise TopologyMismatchError(
            "checkpoint/model mismatch — " + "; ".join(bad[:max_named])
            + extra)


def check_accum_window(state, src_topo, dst_k):
    """Validate the gradient-accumulation window across a topology
    change. A mid-window snapshot (``micro % k_src != 0``) can only
    continue under the SAME ``accumulate_steps`` — the accumulator holds
    k_src-normalized partial contributions. At a window boundary a
    ``k`` change is safe: the accumulator is zeros and the micro counter
    restarts. Returns the (possibly adjusted) micro counter to restore,
    or None when the destination should keep its own."""
    src_k = int((src_topo or {}).get("accumulate_steps") or 0)
    micro = state.get("micro")
    if not src_k or micro is None:
        return micro
    micro = int(micro)
    mid = micro % src_k != 0
    if src_k == int(dst_k):
        return micro
    if mid:
        _count("rejected_loads")
        raise TopologyMismatchError(
            f"accumulate_steps: checkpoint={src_k} vs step={int(dst_k)} "
            f"with a MID-WINDOW accumulator (micro={micro}, "
            f"{micro % src_k}/{src_k} contributions) — resume on "
            f"accumulate_steps={src_k} or restore a window-boundary "
            f"snapshot")
    return 0  # boundary: restart the window count under the new k
