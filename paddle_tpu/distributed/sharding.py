"""Group sharded (ZeRO) API (ref: python/paddle/distributed/sharding/
group_sharded.py, fleet/meta_parallel/sharding/*).

Stage semantics on TPU:
  * stage 1 — optimizer states sharded over the 'sharding' axis (TrainStep
    shards slots; XLA gathers during the fused update);
  * stage 2 — + gradients effectively sharded: with sharded slots the grad
    reduce becomes reduce-scatter in XLA's schedule;
  * stage 3 — + parameters sharded (dist_spec over 'sharding'); XLA inserts
    per-layer all-gathers in forward/backward exactly like the reference's
    stage-3 prefetch.
"""
from __future__ import annotations

import logging

from jax.sharding import PartitionSpec as P

from . import env

logger = logging.getLogger(__name__)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """ref signature: level in {'os', 'os_g', 'p_g_os'}.

    offload=True places optimizer slot states in HOST memory
    (memory_kind='pinned_host'); the compiled step streams them to the chip
    for the update and back (ref: fleet/meta_parallel/sharding/
    group_sharded_stage3.py:84 cpu offload). On a 16G chip this moves the
    8-bytes/param fp32 adam moments off HBM — the single-chip enabler for
    2.7B-class configs.
    """
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level, 1)
    if offload:
        optimizer._offload_opt_states = True
    mesh = env.get_mesh()
    axis = "sharding" if (mesh and mesh.shape.get("sharding", 1) > 1) else (
        "dp" if (mesh and mesh.shape.get("dp", 1) > 1) else None)
    if axis is None:
        return model, optimizer, scaler
    n = mesh.shape[axis]
    if stage >= 3:
        skipped = []
        for name, p in model.named_parameters():
            if getattr(p, "dist_spec", None) is not None:
                continue
            shape = tuple(p.shape)
            if not shape:
                continue
            # shard the largest dim divisible by the axis size — falling
            # back through smaller dims instead of silently keeping the
            # param replicated when only the largest dim is indivisible
            dims = sorted(range(len(shape)), key=lambda i: shape[i],
                          reverse=True)
            dim = next((i for i in dims if shape[i] % n == 0), None)
            if dim is None:
                skipped.append(f"{name}{list(shape)}")
                continue
            spec = [None] * len(shape)
            spec[dim] = axis
            p.dist_spec = P(*spec)
        if skipped:
            logger.warning(
                "group_sharded_parallel stage-%d: %d param(s) stay "
                "replicated (no dim divisible by %s=%d): %s",
                stage, len(skipped), axis, n, ", ".join(skipped))
    optimizer._zero_stage = stage
    optimizer._shard_opt_states_axis = axis
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save
    state = {"model": model.state_dict()}
    if optimizer is not None:
        state["optimizer"] = optimizer.state_dict()
    save(state, output)
