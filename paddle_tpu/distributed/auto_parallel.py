"""Semi-automatic parallelism (ref: python/paddle/distributed/auto_parallel/).

The reference pipeline — shard_tensor annotations -> partitioner -> reshard
pass -> distributed Program (ref: auto_parallel/interface.py,
static/engine.py, static/reshard.py) — maps onto GSPMD: `shard_tensor`
attaches placements and physically places the data, the XLA partitioner
propagates shardings and inserts the collectives the reference's reshard
pass would have emitted, and `to_static` bridges a (layer, loader, loss,
optimizer) tuple into one compiled SPMD train step (`DistModel`).

Placement semantics:
  Shard(d)   — dim d split over the mesh axis at the placement's position.
  Replicate  — full copy on every device along that axis.
  Partial    — each device holds a partial term; the global value is the
               axis-reduction of the locals. Physically the locals live in a
               stacked (axis_size, *shape) buffer sharded over the mesh axis;
               the logical value is reduced ON READ (jnp.sum over the sharded
               axis == psum over ICI) — see PartialTensor/_materialize.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor_impl import Tensor, Parameter
from . import env


class ProcessMesh:
    """ref: auto_parallel/process_mesh.py."""

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.arange(int(np.prod(shape))).reshape(shape)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self._jax_mesh = Mesh(devices, tuple(self.dim_names))
        env.set_mesh(self._jax_mesh)

    @property
    def mesh(self):
        return self._jax_mesh

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and self.shape == other.shape

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


class Shard:
    def __init__(self, dim):
        self.dim = dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate:
    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __repr__(self):
        return "Replicate()"


class Partial:
    def __init__(self, reduce_type="sum"):
        if reduce_type not in ("sum", "avg", "max", "min"):
            raise ValueError(f"unsupported reduce_type {reduce_type}")
        self.reduce_type = reduce_type

    def __eq__(self, other):
        return (isinstance(other, Partial)
                and other.reduce_type == self.reduce_type)

    def __repr__(self):
        return f"Partial({self.reduce_type})"


_REDUCERS = {"sum": jnp.sum, "avg": jnp.mean, "max": jnp.max, "min": jnp.min}


def _placements_to_spec(placements, ndim, mesh):
    spec = [None] * ndim
    for axis_i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            if spec[pl.dim] is not None:
                spec[pl.dim] = (*_as_tuple(spec[pl.dim]),
                                mesh.dim_names[axis_i])
            else:
                spec[pl.dim] = mesh.dim_names[axis_i]
    return P(*spec)


def _as_tuple(x):
    return x if isinstance(x, tuple) else (x,)


def _normalize_placements(placements, mesh):
    pls = list(placements)
    while len(pls) < len(mesh.shape):
        pls.append(Replicate())
    return pls


def _partial_axes(placements, mesh):
    return [(mesh.dim_names[i], pl.reduce_type)
            for i, pl in enumerate(placements) if isinstance(pl, Partial)]


def _materialize(stack, axis_name, reduce_type, mesh, spec):
    """Reduce a (axis_size, *shape) buffer sharded over `axis_name` to the
    logical value — XLA lowers the reduction over the device-sharded axis to
    a psum/pmax over ICI (the reference's r_to_p/partial->replicated reshard,
    ref: auto_parallel/static/reshard_funcs/p_to_r_reshard_func.py)."""
    out_sharding = NamedSharding(mesh, spec)
    red = _REDUCERS[reduce_type]
    return jax.jit(lambda s: red(s, axis=0),
                   out_shardings=out_sharding)(stack)


def shard_tensor(x, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=None):
    """Attach placements and physically place the data (ref:
    auto_parallel/api.py shard_tensor).

    Shard/Replicate place via GSPMD NamedSharding. Partial stores the global
    value on the axis's first device and zeros elsewhere (the reference's
    replicated->partial convention), keeping the stacked locals in
    `_partial_stack`; the logical `_data` is the on-read reduction.
    """
    t = x if isinstance(x, Tensor) else Tensor(x)
    if dtype is not None:
        t = Tensor(t._data.astype(dtype), stop_gradient=t.stop_gradient) \
            if not isinstance(t, Parameter) else t
    placements = _normalize_placements(placements, mesh)
    spec = _placements_to_spec(placements, t._data.ndim, mesh)
    partials = _partial_axes(placements, mesh)
    if partials:
        if len(partials) > 1:
            raise NotImplementedError("at most one Partial axis")
        axis_name, reduce_type = partials[0]
        n = mesh.shape[mesh.dim_names.index(axis_name)]
        # global value on local rank 0, identity elsewhere (zeros for sum)
        if reduce_type in ("max", "min"):
            fill = t._data  # max/min identity: replicate the value
            stack = jnp.stack([t._data] + [fill] * (n - 1))
        else:
            stack = jnp.concatenate(
                [t._data[None], jnp.zeros((n - 1,) + t._data.shape,
                                          t._data.dtype)])
        stack = jax.device_put(
            stack, NamedSharding(mesh.mesh, P(axis_name, *spec)))
        t._data = _materialize(stack, axis_name, reduce_type, mesh.mesh, spec)
        t._partial_stack = (stack, axis_name, reduce_type)
    else:
        t._data = jax.device_put(t._data, NamedSharding(mesh.mesh, spec))
        t._partial_stack = None
    t.dist_spec = spec
    t.placements = placements
    t.process_mesh = mesh
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    return t


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def dtensor_from_local(local, mesh: ProcessMesh, placements):
    """Build a dist tensor from per-device local values (ref:
    auto_parallel/api.py dtensor_from_local).

    For a Partial placement, `local` carries a leading (axis_size,) dim of
    per-device partial terms; the logical value is their on-read reduction
    (psum over the sharded axis). For Shard/Replicate, `local` is the global
    value and this is shard_tensor.
    """
    t = local if isinstance(local, Tensor) else Tensor(local)
    placements = _normalize_placements(placements, mesh)
    partials = _partial_axes(placements, mesh)
    if not partials:
        return shard_tensor(t, mesh, placements)
    if len(partials) > 1:
        raise NotImplementedError("at most one Partial axis")
    axis_name, reduce_type = partials[0]
    n = mesh.shape[mesh.dim_names.index(axis_name)]
    if t._data.shape[0] != n:
        raise ValueError(
            f"local leading dim {t._data.shape[0]} != axis size {n}")
    spec = _placements_to_spec(placements, t._data.ndim - 1, mesh)
    stack = jax.device_put(t._data,
                           NamedSharding(mesh.mesh, P(axis_name, *spec)))
    out = Tensor(_materialize(stack, axis_name, reduce_type, mesh.mesh, spec),
                 stop_gradient=t.stop_gradient)
    out._partial_stack = (stack, axis_name, reduce_type)
    out.dist_spec = spec
    out.placements = placements
    out.process_mesh = mesh
    return out


def reshard(x, mesh: ProcessMesh, placements):
    """Redistribute to new placements (ref: auto_parallel/api.py reshard;
    static/reshard_funcs/*). All transitions are supported:
      Shard/Replicate -> Shard/Replicate : GSPMD device_put (XLA moves data)
      Partial -> Replicate               : reduce the stacked locals (psum)
      Partial -> Shard(d)                : reduce + split (reduce-scatter)
      * -> Partial                       : value on axis rank 0, zeros rest
    """
    t = x if isinstance(x, Tensor) else Tensor(x)
    placements = _normalize_placements(placements, mesh)
    partial_src = getattr(t, "_partial_stack", None)
    want_partial = bool(_partial_axes(placements, mesh))
    spec = _placements_to_spec(placements, t._data.ndim, mesh)

    if want_partial:
        out = shard_tensor(Tensor(t._data), mesh, placements,
                           stop_gradient=t.stop_gradient)
        return out
    data = t._data
    if partial_src is not None:
        stack, axis_name, reduce_type = partial_src
        data = _materialize(stack, axis_name, reduce_type, mesh.mesh, spec)
    else:
        data = jax.device_put(data, NamedSharding(mesh.mesh, spec))
    t2 = Tensor(data, stop_gradient=t.stop_gradient)
    t2.dist_spec = spec
    t2.placements = placements
    t2.process_mesh = mesh
    t2._partial_stack = None
    return t2


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Shard a layer's parameters in place (ref: auto_parallel/api.py
    shard_layer). shard_fn(sublayer_name, sublayer, mesh) may call
    shard_tensor on the sublayer's params; without one, every param is
    replicated onto the mesh (dist_spec set so TrainStep honors it)."""
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    else:
        for _, p in layer.named_parameters():
            shard_tensor(p, process_mesh, [Replicate()])
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None, axis="dp"):
    """Shard optimizer slot states over a mesh axis (ref: auto_parallel/
    api.py shard_optimizer; fleet sharding stage-1 state partitioning).

    Two integration points:
      * compiled path: TrainStep/HybridTrainStep read
        `_shard_opt_states_axis` and emit GSPMD shardings that split every
        replicated param's slots over the axis (ZeRO-1).
      * eager path: slot creation is wrapped so each new slot is placed
        sharded (shard_fn(param, slot_name, array) -> placements may
        override).
    """
    optimizer._shard_opt_states_axis = axis
    mesh = env.get_mesh()
    if mesh is None or axis not in getattr(mesh, "axis_names", ()):
        return optimizer
    n = mesh.shape[axis]
    orig_create = optimizer._create_slots

    def sharded_create(p_data):
        slots = orig_create(p_data)
        out = {}
        for name, arr in slots.items():
            if shard_fn is not None:
                pl = shard_fn(name, arr)
                if pl is not None:
                    out[name] = jax.device_put(arr, NamedSharding(
                        mesh, _placements_to_spec(pl, arr.ndim,
                                                  _MeshView(mesh))))
                    continue
            if arr.ndim >= 1 and arr.shape[0] % n == 0:
                out[name] = jax.device_put(arr, NamedSharding(
                    mesh, P(axis, *([None] * (arr.ndim - 1)))))
            else:
                out[name] = arr
        return out

    optimizer._create_slots = sharded_create
    return optimizer


class _MeshView:
    """Duck-typed ProcessMesh view over a raw jax Mesh (for helpers that
    only need dim_names/shape)."""

    def __init__(self, mesh):
        self.dim_names = list(mesh.axis_names)
        self.shape = [mesh.shape[a] for a in mesh.axis_names]
        self._jax_mesh = mesh

    @property
    def mesh(self):
        return self._jax_mesh


class DistModel:
    """Compiled semi-auto training handle (ref: auto_parallel/api.py
    DistModel / static/engine.py Engine).

    Wraps jit.TrainStep: parameters keep the shardings their `shard_tensor`
    annotations attached (dist_spec), XLA partitions the step, and each
    __call__ runs one SPMD train (or eval) step returning the loss.
    """

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, mesh=None):
        from ..jit.train_step import TrainStep
        self._layer = layer
        self._loader = loader
        self._mode = "train"
        if mesh is None:
            mesh = env.get_mesh()
        self._mesh = mesh
        self._train_step = None
        if loss is not None and optimizer is not None:
            self._train_step = TrainStep(layer, loss, optimizer, mesh=mesh)
        self._loss = loss

    def train(self):
        self._mode = "train"
        self._layer.train()

    def eval(self):
        self._mode = "eval"
        self._layer.eval()

    def __call__(self, *batch):
        inputs, labels = batch[:-1], batch[-1]
        if self._mode == "train":
            if self._train_step is None:
                raise ValueError("DistModel needs loss+optimizer to train")
            return self._train_step(list(inputs), labels)
        out = self._layer(*inputs)
        if self._loss is not None:
            return self._loss(out, labels)
        return out

    def state_dict(self, mode="all"):
        self._sync()
        return self._layer.state_dict()

    def _sync(self):
        if self._train_step is not None and self._train_step._jitted is not None:
            self._train_step.sync_to_model()

    @property
    def dist_main_program(self):
        """HLO text of the compiled step (the Program analog)."""
        if self._train_step is None or self._train_step._jitted is None:
            return None
        return "<compiled XLA SPMD step>"


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """Bridge dygraph semi-auto annotations into one compiled SPMD step
    (ref: auto_parallel/api.py to_static -> DistModel)."""
    return DistModel(layer, loader=loader, loss=loss, optimizer=optimizer,
                     strategy=strategy)
