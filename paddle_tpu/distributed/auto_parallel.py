"""Semi-automatic parallelism (ref: python/paddle/distributed/auto_parallel/).

The reference's shard_tensor annotations + partitioner + reshard pipeline maps
almost one-to-one onto GSPMD: `shard_tensor` attaches a PartitionSpec, the XLA
partitioner propagates shardings and inserts resharding collectives. ProcessMesh
wraps jax.sharding.Mesh.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor_impl import Tensor, Parameter
from . import env


class ProcessMesh:
    """ref: auto_parallel/process_mesh.py."""

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.arange(int(np.prod(shape))).reshape(shape)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self._jax_mesh = Mesh(devices, tuple(self.dim_names))
        env.set_mesh(self._jax_mesh)

    @property
    def mesh(self):
        return self._jax_mesh

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and self.shape == other.shape

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


class Shard:
    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class Partial:
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type


def _placements_to_spec(placements, ndim, mesh):
    spec = [None] * ndim
    for axis_i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            spec[pl.dim] = mesh.dim_names[axis_i]
    return P(*spec)


def shard_tensor(x, mesh: ProcessMesh, placements, dtype=None, stop_gradient=None):
    """Attach a distribution annotation and place the data (ref:
    auto_parallel/api.py shard_tensor)."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    spec = _placements_to_spec(placements, t._data.ndim, mesh)
    sharding = NamedSharding(mesh.mesh, spec)
    t._data = jax.device_put(t._data, sharding)
    if isinstance(t, Parameter) or hasattr(t, "dist_spec"):
        t.dist_spec = spec
    else:
        t._placeholder = spec
    return t


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh: ProcessMesh, placements):
    t = x if isinstance(x, Tensor) else Tensor(x)
    spec = _placements_to_spec(placements, t._data.ndim, mesh)
    t2 = Tensor(jax.device_put(t._data, NamedSharding(mesh.mesh, spec)),
                stop_gradient=t.stop_gradient)
    return t2


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None, output_fn=None):
    """Annotate a layer's params via shard_fn(name, layer, mesh) or replicate."""
    for name, sub in layer.named_sublayers(include_self=True):
        if shard_fn is not None:
            shard_fn(name, sub, process_mesh)
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    optimizer._shard_opt_states_axis = getattr(optimizer, "_shard_opt_states_axis",
                                               None)
    return optimizer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    raise NotImplementedError(
        "auto_parallel.to_static: use paddle_tpu.jit.TrainStep with a mesh; "
        "GSPMD performs the partitioning that the reference's planner does.")
