"""Ring attention — sequence/context parallelism for long sequences.

Capability target: the reference's long-sequence path (sequence parallelism in
fleet + fused attention). TPU-native design follows Ring Attention (Liu et al.)
over the ICI ring: Q stays resident, K/V blocks rotate via `ppermute`, and the
softmax is accumulated online (flash-attention style, fp32 accumulators), so
sequence length scales linearly with the number of chips at O(S/n) memory per
chip and the K/V transfer overlaps compute around the ring.

Also provides the all-to-all variant (DeepSpeed-Ulysses style): resharding
[B, S/n, H, D] -> [B, S, H/n, D] with one `all_to_all` before and after plain
attention — cheaper when H >= n and sequences fit.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import env

_NEG_INF = -1e30


def _block_attn(q, k, v, m_prev, l_prev, acc_prev, block_mask):
    """One online-softmax block update. q:[B,Sq,H,D] k,v:[B,Sk,H,D];
    block_mask broadcastable to [B,H,Sq,Sk] (True=keep) or None."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (d ** -0.5)
    if block_mask is not None:
        s = jnp.where(block_mask, s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1)                      # [B,H,Sq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows: exp(-inf - -inf) -> use where
    p = jnp.exp(s - m_new[..., None])
    if block_mask is not None:
        p = jnp.where(block_mask, p, 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    acc_new = acc_prev * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention_spmd(q, k, v, *, axis_name="sp", causal=True):
    """Inside shard_map manual over `axis_name`. q,k,v: [B, S_local, H, D]
    (local sequence chunk). Returns [B, S_local, H, D]."""
    n = env.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    m = jnp.full((B, H, Sl), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Sl), jnp.float32)
    acc = jnp.zeros((B, H, Sl, D), jnp.float32)

    k_cur, v_cur = k, v
    for step in range(n):
        src = (my - step) % n  # which chunk k_cur/v_cur belong to
        if causal:
            # chunk-level causality: key chunk must not be after query chunk
            q_pos = my * Sl + jnp.arange(Sl)              # global query positions
            k_pos = src * Sl + jnp.arange(Sl)
            mask = (k_pos[None, :] <= q_pos[:, None])     # [Sq, Sk]
            mask = mask[None, None]                        # [1,1,Sq,Sk]
        else:
            mask = None
        m, l, acc = _block_attn(q, k_cur, v_cur, m, l, acc, mask)
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name="sp", causal=True):
    """Host-side wrapper: q,k,v [B, S, H, D] logically; sequence dim sharded
    over `axis_name`. Works with GSPMD-auto other axes."""
    mesh = mesh or env.get_mesh()
    from ..tensor_impl import Tensor, as_tensor_data
    qa, ka, va = (as_tensor_data(t) for t in (q, k, v))
    spec = P(None, axis_name, None, None)
    mapped = env.shard_map_compat(
        functools.partial(ring_attention_spmd, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}))
    out = mapped(qa, ka, va)
    return Tensor(out) if isinstance(q, Tensor) else out


def ulysses_attention_spmd(q, k, v, *, axis_name="sp", causal=True):
    """All-to-all sequence parallelism: exchange seq-shard for head-shard,
    run full-sequence attention per head group, exchange back."""
    n = env.axis_size(axis_name)
    B, Sl, H, D = q.shape
    assert H % n == 0, f"heads {H} not divisible by sp degree {n}"

    def seq2head(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]
        x = x.reshape(B, Sl, n, H // n, D)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=False)
        return x.reshape(B, Sl * n, H // n, D)

    def head2seq(x):
        S = x.shape[1]
        x = x.reshape(B, n, S // n, H // n, D)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=3, tiled=False)
        return x.reshape(B, S // n, H, D)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    S = qh.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh).astype(jnp.float32) * (D ** -0.5)
    if causal:
        cm = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(cm[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vh.astype(jnp.float32)).astype(q.dtype)
    return head2seq(out)


def ulysses_attention(q, k, v, mesh=None, axis_name="sp", causal=True):
    mesh = mesh or env.get_mesh()
    from ..tensor_impl import Tensor, as_tensor_data
    qa, ka, va = (as_tensor_data(t) for t in (q, k, v))
    spec = P(None, axis_name, None, None)
    mapped = env.shard_map_compat(
        functools.partial(ulysses_attention_spmd, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}))
    out = mapped(qa, ka, va)
    return Tensor(out) if isinstance(q, Tensor) else out
