"""Elastic training: failure detection + restart-from-checkpoint harness
(ref: python/paddle/distributed/elastic.py and fleet elastic manager).

The reference's elastic manager watches etcd heartbeats and relaunches ranks.
The SPMD/TPU analog has no per-rank NCCL process to babysit — failure modes
are (a) a host/process dying and (b) the numerics going non-finite. We cover
both with host-local primitives:

  * ``Heartbeat`` / ``HeartbeatMonitor`` — per-rank heartbeat files on shared
    storage; a rank whose file goes stale past ``timeout`` is reported failed
  * ``check_numerics`` / ``NanGuard`` — per-step finite check over a pytree
    (jnp.isfinite reduction, one scalar fetched to host) raising
    ``NonFiniteError``, the per-step guard promised in SURVEY §5
  * ``ElasticAgent`` — runs a training function, and on failure restores the
    latest checkpoint (``incubate.checkpoint.CheckpointManager``) and retries,
    up to ``max_restarts``
"""
from __future__ import annotations

import json
import math
import os
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp


# -- elastic counters (observability "elastic" family) -----------------------
# The mesh-reforming supervisor's event ledger: shrinks/grows/reforms,
# snapshot restores it performed, resume latency, and live gauges (active
# dp, world size, failed ranks). Merged with the reshard-on-load counters
# (distributed/topology.py) into the registry's "elastic" family, so every
# event is visible in one snapshot and on the Prometheus endpoint.

_elastic_lock = threading.Lock()


def _zero_elastic():
    return {"shrinks": 0, "grows": 0, "reforms": 0, "elastic_restores": 0,
            "steps_lost": 0, "resume_latency_s_last": 0.0,
            "resume_latency_s_total": 0.0, "active_dp": 0, "active_pp": 0,
            "world_size": 0, "failed_ranks": 0,
            # serving fleet (serving/elastic.py): mp-group reforms after a
            # chip loss, grow-backs to the original degree, live gauges
            # for groups running below their configured mp / chips
            # currently lost, and reform latency. Per-replica active-mp
            # gauges land as dynamic "active_mp_replica{i}" keys.
            "group_reforms": 0, "grow_backs": 0, "degraded_groups": 0,
            "serving_chips_lost": 0, "reform_latency_s_last": 0.0,
            "reform_latency_s_total": 0.0}


_elastic_counters = _zero_elastic()


def elastic_counters():
    with _elastic_lock:
        return dict(_elastic_counters)


def reset_elastic_counters():
    global _elastic_counters
    with _elastic_lock:
        _elastic_counters = _zero_elastic()


def _ecount(key, n=1):
    with _elastic_lock:
        _elastic_counters[key] += n


def _egauge(key, v):
    with _elastic_lock:
        _elastic_counters[key] = v


class NonFiniteError(RuntimeError):
    """Raised when a watched value contains NaN/Inf."""


def all_finite(*trees):
    """TRACEABLE all-finite check: one fused boolean scalar over every
    inexact leaf of ``trees``, for use INSIDE a jitted step program.

    This is the zero-host-sync counterpart of ``check_numerics``: the
    NanGuard below costs one device->host fetch per guarded step, while the
    compiled anomaly guard (jit.TrainStep, FLAGS_anomaly_policy) fuses this
    reduction into the step executable and returns the flag alongside the
    loss — the host learns about the bad step from the fetch it was already
    doing. Non-float leaves (int tokens, counters) are skipped, matching
    check_numerics.
    """
    ok = jnp.asarray(True)
    for l in jax.tree_util.tree_leaves(trees):
        if hasattr(l, "_data"):
            l = l._data
        arr = jnp.asarray(l)
        if jnp.issubdtype(arr.dtype, jnp.inexact):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(arr)))
    return ok


def check_numerics(tree, name="tensors"):
    """Raise NonFiniteError if any leaf of ``tree`` has a NaN or Inf."""
    arrays = []
    for l in jax.tree_util.tree_leaves(tree):
        if hasattr(l, "_data"):
            l = l._data
        if isinstance(l, float):  # plain python / numpy scalar loss
            if not math.isfinite(l):
                raise NonFiniteError(f"non-finite value detected in {name}")
            continue
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact):
            arrays.append(l)
    if not arrays:
        return
    ok = True
    for l in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(l)))
    if not bool(ok):
        raise NonFiniteError(f"non-finite value detected in {name}")


class NanGuard:
    """Context-free step guard: ``guard(loss, grads)`` every N steps."""

    def __init__(self, every_n_steps=1):
        self.every = max(1, int(every_n_steps))
        self._step = 0

    def __call__(self, *trees):
        self._step += 1
        if self._step % self.every == 0:
            check_numerics(trees, name=f"step {self._step}")


class Heartbeat:
    """Writes ``{dir}/hb_{rank}.json`` every ``interval`` seconds."""

    def __init__(self, directory, rank=0, interval=1.0):
        self.directory = os.fspath(directory)
        self.rank = int(rank)
        self.interval = float(interval)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(self.directory, f"hb_{self.rank}.json")
        self._step = 0
        self._status = "running"
        self._stop = threading.Event()
        self._thread = None
        self._write_lock = threading.Lock()

    def beat(self, step=None, status=None):
        with self._write_lock:  # loop thread + user beat(step=...) both write
            if step is not None:
                self._step = int(step)
            if status is not None:
                self._status = status
            from ..utils import fault_injection as _fi
            if _fi.maybe_drop_heartbeat(self.rank):
                return  # chaos: frozen-process simulation — file goes stale
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"ts": time.time(), "rank": self.rank,
                           "step": self._step, "status": self._status}, f)
            os.replace(tmp, self._path)

    def start(self):
        if self._thread is not None:
            return self  # already beating
        self._stop.clear()  # restartable after stop() (elastic retries)
        self._status = "running"
        self.beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self, status="stopped"):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.beat(status=status)


class HeartbeatMonitor:
    """Watches heartbeat files for a SET of ranks (default: ``0 ..
    world_size-1``). The watched set is mutable — ``resize()`` /
    ``set_ranks()`` — because an elastic mesh changes shape at runtime: a
    monitor pinned to its construction-time world would report the
    retired ranks of a shrunk mesh as failed forever (and never watch the
    ranks a grow adds)."""

    def __init__(self, directory, world_size, timeout=10.0):
        self.directory = os.fspath(directory)
        self.ranks = tuple(range(int(world_size)))
        self.timeout = float(timeout)

    @property
    def world_size(self):
        return len(self.ranks)

    @world_size.setter
    def world_size(self, n):  # legacy assignment keeps working
        self.ranks = tuple(range(int(n)))

    def resize(self, world_size):
        """Watch ranks ``0 .. world_size-1`` (a grown/shrunk contiguous
        world)."""
        self.world_size = int(world_size)
        return self

    def set_ranks(self, ranks):
        """Watch exactly ``ranks`` (a re-formed mesh's surviving rank set —
        possibly non-contiguous after a mid-world chip loss). Retired
        ranks leave the watch set, so ``failed_ranks()`` stays consistent
        with the CURRENT mesh instead of flagging them forever."""
        self.ranks = tuple(sorted(int(r) for r in ranks))
        return self

    def poll(self, ranks=None):
        """Return {rank: info|None} — None means no heartbeat file yet.
        ``ranks`` overrides the watched set for one poll (e.g. probing
        whether RETIRED ranks have come back, without re-admitting them
        to failure detection)."""
        out = {}
        for r in (self.ranks if ranks is None else ranks):
            path = os.path.join(self.directory, f"hb_{int(r)}.json")
            try:
                with open(path) as f:
                    info = json.load(f)
                info["age"] = time.time() - info["ts"]
                out[int(r)] = info
            except (OSError, ValueError):
                out[int(r)] = None
        return out

    def failed_ranks(self, ranks=None):
        """Ranks that are missing, stale past timeout, or marked failed."""
        bad = []
        for r, info in self.poll(ranks).items():
            if info is None or info["age"] > self.timeout \
                    or info.get("status") == "failed":
                bad.append(r)
        return bad

    def wait_alive(self, deadline=30.0):
        """Block until every rank has a fresh heartbeat (startup barrier)."""
        t0 = time.time()
        while time.time() - t0 < deadline:
            if not self.failed_ranks():
                return True
            time.sleep(0.05)
        return False


class ElasticAgent:
    """Run ``train_fn(state, start_step) -> final_state`` with auto-restart.

    On any exception from ``train_fn`` the agent restores the latest
    checkpoint from ``ckpt`` and re-invokes it, up to ``max_restarts`` times.
    ``train_fn`` receives the restored state pytree (or ``initial_state`` when
    no checkpoint exists) and the step to resume from; it is responsible for
    calling ``ckpt.save(step, state)`` periodically.

    Preemption (``incubate.checkpoint.Preempted`` from the SIGTERM hook, or
    ``utils.fault_injection.Preemption`` from the chaos harness) derives
    from BaseException on purpose: it unwinds THROUGH this restart loop —
    a preempted process must exit and be resumed by its scheduler, not
    burn its restart budget retraining in a machine about to disappear.
    """

    def __init__(self, train_fn, ckpt, initial_state=None, max_restarts=3,
                 heartbeat=None, on_restart=None):
        self.train_fn = train_fn
        self.ckpt = ckpt
        self.initial_state = initial_state
        self.max_restarts = int(max_restarts)
        self.heartbeat = heartbeat
        self.on_restart = on_restart
        self.restarts = 0

    def run(self):
        while True:
            # restore(None) quarantines corrupt checkpoints and falls back
            # to the previous good step (the crash may have been mid-write).
            # Pair start_step with the step the restore ACTUALLY loaded —
            # latest_step() may still list a newer unreadable-but-kept step
            state = self.ckpt.restore(None)
            if state is not None:
                step = (self.ckpt.last_restored_step
                        if hasattr(self.ckpt, "last_restored_step")
                        else self.ckpt.latest_step())  # duck-typed managers
            else:
                step = None
                state = self.initial_state
            start_step = 0 if step is None else int(step)
            try:
                if self.heartbeat is not None:
                    self.heartbeat.start()
                result = self.train_fn(state, start_step)
                if self.heartbeat is not None:
                    self.heartbeat.stop(status="finished")
                return result
            except Exception as e:  # noqa: BLE001 — any training failure restarts
                if self.heartbeat is not None:
                    self.heartbeat.stop(status="failed")
                try:
                    self.ckpt.wait()
                except Exception:  # stale async-save IO error must not
                    pass           # preempt the restart: older ckpts are valid
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise RuntimeError(
                        f"elastic: giving up after {self.restarts - 1} restarts") from e
                if self.on_restart is not None:
                    self.on_restart(self.restarts, e)


class ElasticMeshSupervisor:
    """Mesh-reforming elastic training: survive chip/rank loss by
    re-forming the largest viable mesh from the survivors and resuming
    from the latest good snapshot through the reshard-on-load path.

    ``ElasticAgent`` restarts the SAME-shaped job; this supervisor closes
    the remaining gap — on TPU pods the thing that actually disappears is
    a host with its chips, and the job that comes back is SMALLER. Per
    step boundary it:

      1. **detects** rank loss: the deterministic chip-loss schedule
         (``utils.fault_injection.lost_ranks`` — injected device failure)
         and, with ``heartbeat_dir`` set, ranks whose heartbeat files went
         stale past ``heartbeat_timeout`` (a frozen host looks exactly
         like this). Retired ranks are probed for RETURN the same way
         (fresh heartbeats / ``chip_return_at``), so the mesh grows back;
      2. **re-forms** the mesh: the largest dp with ``min_dp <= dp <=
         survivors`` that divides ``global_batch`` (the global batch must
         still shard evenly), over the surviving devices. With a ``pp``
         target, the largest ``pp <= target`` dividing ``num_layers``
         (stages stay layer-balanced) that still leaves a viable dp is
         chosen FIRST — a pp4×dp2 job that loses a chip resumes as
         pp2×dp<=3 and grows back to pp4 when the chip returns;
      3. **rebuilds** the TrainStep through ``step_factory(mesh)`` —
         memoized per (dp, pp, device-set), so growing back to a topology
         seen before reuses its compiled executables;
      4. **resumes** from ``ckpt.restore(None)``: the packed dp-sharded
         optimizer slots reshard to the new axis size on load
         (distributed/topology.py), the RNG stream and data position
         continue in global terms, and training re-serves the batches
         after the snapshot — zero manual steps from kill to progress.

    Every event (shrink/grow/reform, restore, resume latency, steps
    re-executed) lands in ``elastic_counters()`` → the observability
    registry's "elastic" family → the Prometheus endpoint.

    Single-process notes: with ``heartbeat_dir`` set the supervisor also
    BEATS for every world rank each boundary — the single-controller
    simulation of per-host heartbeat daemons (the fault plan's
    ``stale_heartbeat_ranks`` freezes individual ranks). On a real
    multi-host pod each host runs its own ``Heartbeat``; only the
    monitoring half applies.
    """

    def __init__(self, step_factory, ckpt, global_batch, devices=None,
                 save_every=None, min_dp=None, grow=None, max_reforms=16,
                 heartbeat_dir=None, heartbeat_timeout=None, on_event=None,
                 pp=1, num_layers=None, quarantine=False):
        from .. import flags as _flags
        F = _flags._FLAGS
        self.step_factory = step_factory
        self.ckpt = ckpt
        self.global_batch = int(global_batch)
        self.devices = list(devices if devices is not None else jax.devices())
        self.world = len(self.devices)
        # pipelined elastic training: ``pp`` is the TARGET stage count; on
        # chip loss the mesh re-forms to the largest pp <= target that
        # divides ``num_layers`` (stages must stay layer-balanced) and
        # still leaves a viable dp for the survivors — growing back toward
        # the target when chips return
        self.pp_target = max(1, int(pp))
        self.num_layers = None if num_layers is None else int(num_layers)
        self.pp = 0                 # pp degree of the CURRENT mesh
        self.save_every = int(F.get("FLAGS_elastic_snapshot_every", 4)
                              if save_every is None else save_every)
        self.min_dp = int(F.get("FLAGS_elastic_min_dp", 1)
                          if min_dp is None else min_dp)
        self.grow = bool(F.get("FLAGS_elastic_grow", True)
                         if grow is None else grow)
        self.max_reforms = int(max_reforms)
        # ``quarantine`` policy (distributed/integrity.py): a chip whose
        # replica needed >= FLAGS_sdc_quarantine_threshold peer repairs is
        # a repeat silent-corruption offender — treat it as LOST and
        # re-form the mesh over the survivors (the ordinary reform path),
        # instead of letting it keep flipping bits or rewinding everyone
        # to disk. Quarantined ranks are sticky regardless of ``grow``
        # (the signal is accumulated damage, not a recovered heartbeat).
        self.quarantine = bool(quarantine)
        self.on_event = on_event
        self.events = []            # audit trail of reform events
        self.step = None            # current TrainStep
        self.dp = 0
        self.active = ()            # ranks of the current mesh
        self.failed = frozenset()
        self.reforms = 0
        self._steps = {}            # (dp, device ids) -> TrainStep memo
        self.monitor = None
        self._beats = {}
        if heartbeat_dir is not None:
            timeout = float(F.get("FLAGS_elastic_heartbeat_timeout", 5.0)
                            if heartbeat_timeout is None
                            else heartbeat_timeout)
            self.monitor = HeartbeatMonitor(heartbeat_dir, self.world,
                                            timeout=timeout)
            self._beats = {r: Heartbeat(heartbeat_dir, rank=r)
                           for r in range(self.world)}
        _egauge("world_size", self.world)

    # -- detection -----------------------------------------------------------
    def _beat_all(self, step):
        """Single-process heartbeat simulation: beat for every world rank
        (the fault plan drops frozen ranks' writes, so their files age)."""
        for hb in self._beats.values():
            hb.beat(step=step)

    def _detect(self, step):
        """The failed rank set as of ``step``: injected chip loss
        (``lost_ranks`` — its ``chip_return_at`` schedule re-admits) plus
        ranks whose heartbeat is stale RIGHT NOW. With ``grow`` enabled a
        previously-failed rank whose signal recovered simply drops out of
        the set — the caller sees a smaller set and grows the mesh back;
        with ``grow`` disabled failures are sticky."""
        from ..utils import fault_injection as _fi
        lost = set(_fi.lost_ranks(step)) & set(range(self.world))
        stale = set()
        if self.monitor is not None:
            candidates = [r for r in range(self.world) if r not in lost]
            stale = set(self.monitor.failed_ranks(candidates))
        failed = lost | stale
        if self.quarantine:
            from . import integrity as _integrity
            failed |= set(_integrity.quarantined_ranks()) \
                & set(range(self.world))
        if not self.grow:
            failed |= set(self.failed)
        return frozenset(failed)

    def scrub(self, max_steps=None):
        """Delegate an at-rest integrity scrub to the attached checkpoint
        manager (see CheckpointManager.scrub) — the supervisor-cadence
        entry point beside the opportunistic ``_prune`` hook."""
        if self.ckpt is None:
            return {"scrubbed": 0, "rot": []}
        return self.ckpt.scrub(max_steps=max_steps)

    # -- mesh re-forming -----------------------------------------------------
    def viable_dp(self, n_survivors):
        """Largest dp that the survivors can host AND that divides the
        global batch (the batch must keep sharding evenly over the dp
        axis). Raises with the constraint named when none exists."""
        for d in range(min(int(n_survivors), self.world), 0, -1):
            if d < self.min_dp:
                break
            if self.global_batch % d == 0:
                return d
        raise RuntimeError(
            f"elastic: no viable mesh from {n_survivors} surviving ranks "
            f"(min_dp={self.min_dp}, global_batch={self.global_batch})")

    def viable_pp(self, n_survivors):
        """Largest pp with ``pp <= pp_target`` that divides ``num_layers``
        AND leaves the survivors a viable dp (``dp*pp <= survivors`` with
        ``viable_dp`` constraints). pp=1 is always layer-balanced, so a
        plan exists whenever plain-dp elastic would find one."""
        for p in range(min(self.pp_target, max(1, int(n_survivors))), 0, -1):
            if self.num_layers is not None and self.num_layers % p:
                continue
            if int(n_survivors) // p >= self.min_dp:
                return p
        raise RuntimeError(
            f"elastic: no viable mesh from {n_survivors} surviving ranks "
            f"(pp_target={self.pp_target}, num_layers={self.num_layers}, "
            f"min_dp={self.min_dp})")

    def _plan_active(self, failed):
        """(dp, pp, active ranks) the mesh would re-form to under
        ``failed`` — the cheap what-if ``run()`` uses to skip reforms
        whose active set is unchanged (e.g. a retired spare flapping
        back)."""
        survivors = [r for r in range(self.world) if r not in failed]
        pp = self.viable_pp(len(survivors))
        dp = self.viable_dp(len(survivors) // pp)
        return dp, pp, tuple(survivors[:dp * pp])

    def _reform(self, failed, target_step):
        from . import env as dist_env
        t0 = time.perf_counter()
        dp, pp, active = self._plan_active(failed)
        prev_n = self.dp * self.pp
        kind = ("start" if self.dp == 0 else
                "shrink" if dp * pp < prev_n else
                "grow" if dp * pp > prev_n else "reform")
        devs = [self.devices[r] for r in active]
        if kind == "grow" and self.step is not None \
                and not (set(failed) & set(self.active)):
            # a grow that lost NO currently-active rank keeps every live
            # shard healthy: snapshot the running step FIRST, so the
            # resume is free — no rolled-back steps — and never falls
            # back to a stale snapshot (or none at all). A simultaneous
            # active-rank loss takes the disk-restore path instead (its
            # shards may be gone).
            try:
                self.ckpt.wait()
            except Exception:
                pass  # a failed async save must not block the grow
            self.ckpt.save(self.step._step, self.step.state_dict(),
                           blocking=True)
        mesh = dist_env.create_hybrid_mesh(dp=dp, pp=pp, devices=devs)
        key = (dp, pp,
               tuple(getattr(d, "id", i) for i, d in enumerate(devs)))
        state = self.ckpt.restore(None)
        step = self._steps.get(key)
        if step is None or state is None:
            # no snapshot to restore: NEVER resume a memoized step's stale
            # in-memory state — rebuild fresh from the factory (step 0)
            step = self.step_factory(mesh)
            self._steps[key] = step
        restored = None
        if state is not None:
            step.load_state_dict(state)
            restored = step._step
            _ecount("elastic_restores")
            _ecount("steps_lost", max(0, int(target_step) - restored))
        elif kind != "start":
            # fresh restart with no snapshot: EVERYTHING re-executes —
            # the costliest reform must not report zero steps lost
            _ecount("steps_lost", int(target_step))
        step.attach_checkpoint(self.ckpt, save_every=self.save_every)
        if self.monitor is not None:
            self.monitor.set_ranks(active)
        self.step, self.dp, self.pp = step, dp, pp
        self.active, self.failed = tuple(active), frozenset(failed)
        if kind != "start":
            self.reforms += 1
            if self.reforms > self.max_reforms:
                raise RuntimeError(
                    f"elastic: giving up after {self.max_reforms} mesh "
                    f"reforms")
            _ecount("reforms")
            if kind == "shrink":
                _ecount("shrinks")
            elif kind == "grow":
                _ecount("grows")
        dt = time.perf_counter() - t0
        _egauge("resume_latency_s_last", dt)
        _ecount("resume_latency_s_total", dt)
        _egauge("active_dp", dp)
        _egauge("active_pp", pp)
        _egauge("failed_ranks", len(failed))
        event = {"kind": kind, "dp": dp, "pp": pp, "failed": sorted(failed),
                 "restored_step": restored, "fresh_start": state is None,
                 "latency_s": dt}
        self.events.append(event)
        if self.on_event is not None:
            self.on_event(event)
        return step

    # -- driving -------------------------------------------------------------
    def run(self, batch_fn, steps):
        """Train until ``steps`` total TrainStep CALLS, surviving topology
        changes (under ``accumulate_steps=k`` each call is one micro-batch,
        so the run performs ``steps/k`` optimizer updates — the counter is
        ``TrainStep._step``). ``batch_fn(step) -> (inputs, labels)`` must be a
        deterministic function of the GLOBAL step (numpy arrays of the
        global batch): after a restore the supervisor re-serves the
        batches following the snapshot, continuing the exact sample
        sequence on whatever mesh survived. Returns the final TrainStep
        (``.step`` stays live for inspection)."""
        from ..tensor_impl import Tensor
        steps = int(steps)
        if self.step is None:
            self._beat_all(0)  # files exist before the first staleness poll
            self._reform(self._detect(0), target_step=0)
        while self.step._step < steps:
            t = self.step._step
            self._beat_all(t)
            failed = self._detect(t)
            if failed != self.failed:
                if self._plan_active(failed)[2] == self.active:
                    # the active mesh is unchanged (a retired spare came
                    # back / another spare died): no reform — tearing
                    # down the live healthy step would discard progress
                    self.failed = frozenset(failed)
                    _egauge("failed_ranks", len(failed))
                else:
                    self._reform(failed, target_step=t)
                    continue
            x, y = batch_fn(t)
            self.step(Tensor(np.asarray(x)), Tensor(np.asarray(y)))
        return self.step
