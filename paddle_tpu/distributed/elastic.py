"""Elastic training: failure detection + restart-from-checkpoint harness
(ref: python/paddle/distributed/elastic.py and fleet elastic manager).

The reference's elastic manager watches etcd heartbeats and relaunches ranks.
The SPMD/TPU analog has no per-rank NCCL process to babysit — failure modes
are (a) a host/process dying and (b) the numerics going non-finite. We cover
both with host-local primitives:

  * ``Heartbeat`` / ``HeartbeatMonitor`` — per-rank heartbeat files on shared
    storage; a rank whose file goes stale past ``timeout`` is reported failed
  * ``check_numerics`` / ``NanGuard`` — per-step finite check over a pytree
    (jnp.isfinite reduction, one scalar fetched to host) raising
    ``NonFiniteError``, the per-step guard promised in SURVEY §5
  * ``ElasticAgent`` — runs a training function, and on failure restores the
    latest checkpoint (``incubate.checkpoint.CheckpointManager``) and retries,
    up to ``max_restarts``
"""
from __future__ import annotations

import json
import math
import os
import threading
import time

import jax
import jax.numpy as jnp


class NonFiniteError(RuntimeError):
    """Raised when a watched value contains NaN/Inf."""


def all_finite(*trees):
    """TRACEABLE all-finite check: one fused boolean scalar over every
    inexact leaf of ``trees``, for use INSIDE a jitted step program.

    This is the zero-host-sync counterpart of ``check_numerics``: the
    NanGuard below costs one device->host fetch per guarded step, while the
    compiled anomaly guard (jit.TrainStep, FLAGS_anomaly_policy) fuses this
    reduction into the step executable and returns the flag alongside the
    loss — the host learns about the bad step from the fetch it was already
    doing. Non-float leaves (int tokens, counters) are skipped, matching
    check_numerics.
    """
    ok = jnp.asarray(True)
    for l in jax.tree_util.tree_leaves(trees):
        if hasattr(l, "_data"):
            l = l._data
        arr = jnp.asarray(l)
        if jnp.issubdtype(arr.dtype, jnp.inexact):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(arr)))
    return ok


def check_numerics(tree, name="tensors"):
    """Raise NonFiniteError if any leaf of ``tree`` has a NaN or Inf."""
    arrays = []
    for l in jax.tree_util.tree_leaves(tree):
        if hasattr(l, "_data"):
            l = l._data
        if isinstance(l, float):  # plain python / numpy scalar loss
            if not math.isfinite(l):
                raise NonFiniteError(f"non-finite value detected in {name}")
            continue
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact):
            arrays.append(l)
    if not arrays:
        return
    ok = True
    for l in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(l)))
    if not bool(ok):
        raise NonFiniteError(f"non-finite value detected in {name}")


class NanGuard:
    """Context-free step guard: ``guard(loss, grads)`` every N steps."""

    def __init__(self, every_n_steps=1):
        self.every = max(1, int(every_n_steps))
        self._step = 0

    def __call__(self, *trees):
        self._step += 1
        if self._step % self.every == 0:
            check_numerics(trees, name=f"step {self._step}")


class Heartbeat:
    """Writes ``{dir}/hb_{rank}.json`` every ``interval`` seconds."""

    def __init__(self, directory, rank=0, interval=1.0):
        self.directory = os.fspath(directory)
        self.rank = int(rank)
        self.interval = float(interval)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(self.directory, f"hb_{self.rank}.json")
        self._step = 0
        self._status = "running"
        self._stop = threading.Event()
        self._thread = None
        self._write_lock = threading.Lock()

    def beat(self, step=None, status=None):
        with self._write_lock:  # loop thread + user beat(step=...) both write
            if step is not None:
                self._step = int(step)
            if status is not None:
                self._status = status
            from ..utils import fault_injection as _fi
            if _fi.maybe_drop_heartbeat(self.rank):
                return  # chaos: frozen-process simulation — file goes stale
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"ts": time.time(), "rank": self.rank,
                           "step": self._step, "status": self._status}, f)
            os.replace(tmp, self._path)

    def start(self):
        if self._thread is not None:
            return self  # already beating
        self._stop.clear()  # restartable after stop() (elastic retries)
        self._status = "running"
        self.beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self, status="stopped"):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.beat(status=status)


class HeartbeatMonitor:
    """Watches heartbeat files for ``world_size`` ranks."""

    def __init__(self, directory, world_size, timeout=10.0):
        self.directory = os.fspath(directory)
        self.world_size = int(world_size)
        self.timeout = float(timeout)

    def poll(self):
        """Return {rank: info|None} — None means no heartbeat file yet."""
        out = {}
        for r in range(self.world_size):
            path = os.path.join(self.directory, f"hb_{r}.json")
            try:
                with open(path) as f:
                    info = json.load(f)
                info["age"] = time.time() - info["ts"]
                out[r] = info
            except (OSError, ValueError):
                out[r] = None
        return out

    def failed_ranks(self):
        """Ranks that are missing, stale past timeout, or marked failed."""
        bad = []
        for r, info in self.poll().items():
            if info is None or info["age"] > self.timeout \
                    or info.get("status") == "failed":
                bad.append(r)
        return bad

    def wait_alive(self, deadline=30.0):
        """Block until every rank has a fresh heartbeat (startup barrier)."""
        t0 = time.time()
        while time.time() - t0 < deadline:
            if not self.failed_ranks():
                return True
            time.sleep(0.05)
        return False


class ElasticAgent:
    """Run ``train_fn(state, start_step) -> final_state`` with auto-restart.

    On any exception from ``train_fn`` the agent restores the latest
    checkpoint from ``ckpt`` and re-invokes it, up to ``max_restarts`` times.
    ``train_fn`` receives the restored state pytree (or ``initial_state`` when
    no checkpoint exists) and the step to resume from; it is responsible for
    calling ``ckpt.save(step, state)`` periodically.

    Preemption (``incubate.checkpoint.Preempted`` from the SIGTERM hook, or
    ``utils.fault_injection.Preemption`` from the chaos harness) derives
    from BaseException on purpose: it unwinds THROUGH this restart loop —
    a preempted process must exit and be resumed by its scheduler, not
    burn its restart budget retraining in a machine about to disappear.
    """

    def __init__(self, train_fn, ckpt, initial_state=None, max_restarts=3,
                 heartbeat=None, on_restart=None):
        self.train_fn = train_fn
        self.ckpt = ckpt
        self.initial_state = initial_state
        self.max_restarts = int(max_restarts)
        self.heartbeat = heartbeat
        self.on_restart = on_restart
        self.restarts = 0

    def run(self):
        while True:
            # restore(None) quarantines corrupt checkpoints and falls back
            # to the previous good step (the crash may have been mid-write).
            # Pair start_step with the step the restore ACTUALLY loaded —
            # latest_step() may still list a newer unreadable-but-kept step
            state = self.ckpt.restore(None)
            if state is not None:
                step = (self.ckpt.last_restored_step
                        if hasattr(self.ckpt, "last_restored_step")
                        else self.ckpt.latest_step())  # duck-typed managers
            else:
                step = None
                state = self.initial_state
            start_step = 0 if step is None else int(step)
            try:
                if self.heartbeat is not None:
                    self.heartbeat.start()
                result = self.train_fn(state, start_step)
                if self.heartbeat is not None:
                    self.heartbeat.stop(status="finished")
                return result
            except Exception as e:  # noqa: BLE001 — any training failure restarts
                if self.heartbeat is not None:
                    self.heartbeat.stop(status="failed")
                try:
                    self.ckpt.wait()
                except Exception:  # stale async-save IO error must not
                    pass           # preempt the restart: older ckpts are valid
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise RuntimeError(
                        f"elastic: giving up after {self.restarts - 1} restarts") from e
                if self.on_restart is not None:
                    self.on_restart(self.restarts, e)
