"""Tensor-parallel (model-parallel) layers.

Re-design of fleet.meta_parallel.parallel_layers.mp_layers (ref:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py).

The reference splits weights per rank and calls NCCL allreduce/identity in
forward/backward. TPU-native: weights carry GSPMD `dist_spec` PartitionSpecs
over the 'mp' mesh axis; XLA partitions the matmuls onto the MXU of each chip
and inserts the reduce/identity collectives over ICI automatically. Layer code
stays rank-agnostic (full logical shapes), eager single-chip behavior is
identical to Linear/Embedding.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...nn.layer_base import Layer
from ...nn import functional as F
from ...nn import initializer as I
from ...dispatch import apply as _apply
from .. import env


def _constrain(t, spec=None, last_axis=None):
    """Sharding constraint inside jit when a mesh is active; no-op eagerly.
    `last_axis='mp'` builds a rank-adaptive spec sharding the last dim."""
    mesh = env.get_mesh()
    if mesh is None:
        return t
    from ..collective import _in_spmd

    def f(a):
        s = spec if last_axis is None else P(*([None] * (a.ndim - 1)), last_axis)
        s = s if s is not None else P()
        # a constraint whose axes are bound manually (shard_map — e.g.
        # grad_comm's explicit dp step, or the pipeline's 'pp') is invalid
        # and meaningless: the array is already a per-device shard there.
        # Axes still in GSPMD-auto mode (partial-manual regions) keep their
        # constraints. A replicated P() constraint only survives when some
        # axis is still auto.
        named = {ax for part in s for grp in
                 (part if isinstance(part, tuple) else (part,),)
                 for ax in grp if ax is not None}
        if named:
            if any(_in_spmd(ax) for ax in named):
                return a
        elif all(_in_spmd(ax) for ax in mesh.axis_names):
            return a
        return jax.lax.with_sharding_constraint(
            a, jax.sharding.NamedSharding(mesh, s))
    try:
        return _apply(f, t, op_name="shard_constraint")
    except Exception:
        return t


class ColumnParallelLinear(Layer):
    """Output-dim sharded linear: weight [in, out] spec P(None, 'mp').
    gather_output=True adds an all-gather (GSPMD emits it from the output
    constraint)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr, dtype=self._dtype)
        self.weight.dist_spec = P(None, "mp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, dtype=self._dtype, is_bias=True)
            self.bias.dist_spec = P("mp")
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _constrain(out, P())  # logically replicated output
        else:
            out = _constrain(out, last_axis="mp")
        return out


class RowParallelLinear(Layer):
    """Input-dim sharded linear: weight [in, out] spec P('mp', None); the
    partial products are reduced by XLA (psum over 'mp')."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr, dtype=self._dtype)
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, dtype=self._dtype, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, last_axis="mp")
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, P())


class VocabParallelEmbedding(Layer):
    """Vocab-dim sharded embedding: weight [V, H] spec P('mp', None)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            dtype=self._dtype, default_initializer=I.XavierNormal())
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, P())


class ParallelCrossEntropy(Layer):
    """Softmax-CE over a vocab-sharded logits tensor (ref mp_layers
    ParallelCrossEntropy / c_softmax_with_cross_entropy). GSPMD partitions the
    logsumexp reduction; code is the plain formula on logical shapes."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


def split(x, num_or_sections, axis=0, group=None):
    """paddle.distributed.split parity for weight splitting — TPU model keeps
    logical tensors; returns the input annotated for sharding."""
    return x


def mp_allreduce(x, group=None):
    from ..collective import all_reduce
    return all_reduce(x, group=group or "mp")
