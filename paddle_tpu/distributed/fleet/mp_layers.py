"""Tensor-parallel (model-parallel) layers.

Re-design of fleet.meta_parallel.parallel_layers.mp_layers (ref:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py).

The reference splits weights per rank and calls NCCL allreduce/identity in
forward/backward. TPU-native: weights carry GSPMD `dist_spec` PartitionSpecs
over the 'mp' mesh axis; XLA partitions the matmuls onto the MXU of each chip
and inserts the reduce/identity collectives over ICI automatically. Layer code
stays rank-agnostic (full logical shapes), eager single-chip behavior is
identical to Linear/Embedding.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...nn.layer_base import Layer
from ...nn import functional as F
from ...nn import initializer as I
from ...dispatch import apply as _apply
from .. import env


def _constrain(t, spec=None, last_axis=None, seq_axis=None):
    """Sharding constraint inside jit when a mesh is active; no-op eagerly.
    `last_axis='mp'` builds a rank-adaptive spec sharding the last dim;
    `seq_axis='mp'` shards the second-to-last (sequence) dim — the
    sequence-parallel activation layout."""
    mesh = env.get_mesh()
    if mesh is None:
        return t
    from ..collective import _in_spmd

    def f(a):
        if last_axis is not None:
            s = P(*([None] * (a.ndim - 1)), last_axis)
        elif seq_axis is not None and a.ndim >= 2:
            s = P(*([None] * (a.ndim - 2)), seq_axis, None)
        else:
            s = spec
        s = s if s is not None else P()
        # a constraint whose axes are bound manually (shard_map — e.g.
        # grad_comm's explicit dp step, or the pipeline's 'pp') is invalid
        # and meaningless: the array is already a per-device shard there.
        # Axes still in GSPMD-auto mode (partial-manual regions) keep their
        # constraints. A replicated P() constraint names the WHOLE mesh —
        # including any manually-bound axis — so it only survives when no
        # axis is manual (the jax 0.4.x partitioner aborts on a replicated
        # constraint inside a partial-manual region: hlo_sharding_util
        # IsManualSubgroup check).
        named = {ax for part in s for grp in
                 (part if isinstance(part, tuple) else (part,),)
                 for ax in grp if ax is not None}
        if named:
            if any(_in_spmd(ax) for ax in named):
                return a
        elif any(_in_spmd(ax) for ax in mesh.axis_names):
            return a
        return jax.lax.with_sharding_constraint(
            a, jax.sharding.NamedSharding(mesh, s))
    try:
        return _apply(f, t, op_name="shard_constraint")
    except Exception:
        return t


class ColumnParallelLinear(Layer):
    """Output-dim sharded linear: weight [in, out] spec P(None, 'mp').
    gather_output=True adds an all-gather (GSPMD emits it from the output
    constraint)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr, dtype=self._dtype)
        self.weight.dist_spec = P(None, "mp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, dtype=self._dtype, is_bias=True)
            self.bias.dist_spec = P("mp")
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        from .. import tp_overlap as _tp
        mesh = env.get_mesh()
        if (_tp.layer_schedule(mesh) in ("explicit", "fused")
                and _tp.layer_shapes_ok(x, self.weight, mesh, column=True)):
            # ring-decomposed (or Pallas-fused) all-gather+GEMM (seq-sharded
            # input arrives from the previous RowParallel's reduce-scatter)
            gather = self.gather_output
            if self.bias is not None:
                return _apply(
                    lambda xd, wd, bd: _tp.column_linear(xd, wd, bd, mesh,
                                                         gather),
                    x, self.weight, self.bias, op_name="column_mp_overlap")
            return _apply(
                lambda xd, wd: _tp.column_linear(xd, wd, None, mesh, gather),
                x, self.weight, op_name="column_mp_overlap")
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _constrain(out, P())  # logically replicated output
        else:
            out = _constrain(out, last_axis="mp")
        return out


class RowParallelLinear(Layer):
    """Input-dim sharded linear: weight [in, out] spec P('mp', None); the
    partial products are reduced by XLA (psum over 'mp')."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr, dtype=self._dtype)
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, dtype=self._dtype, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        from .. import tp_overlap as _tp
        mesh = env.get_mesh()
        mode = _tp.layer_schedule(mesh)
        if (mode in ("explicit", "fused")
                and _tp.layer_shapes_ok(x, self.weight, mesh, column=False)):
            # GEMM streaming partial products into a pipelined ring (or
            # in-kernel) reduce-scatter; output lands seq-sharded
            if self.bias is not None:
                return _apply(
                    lambda xd, wd, bd: _tp.row_linear(xd, wd, bd, mesh),
                    x, self.weight, self.bias, op_name="row_mp_overlap")
            return _apply(lambda xd, wd: _tp.row_linear(xd, wd, None, mesh),
                          x, self.weight, op_name="row_mp_overlap")
        if self.input_is_parallel:
            x = _constrain(x, last_axis="mp")
        out = F.linear(x, self.weight, self.bias)
        if mode == "seq" and getattr(out, "ndim", 0) >= 3:
            # sequence parallelism under GSPMD: constraining the reduced
            # output seq-sharded turns the partitioner's all-reduce into a
            # reduce-scatter and keeps downstream norms/residuals at 1/mp
            return _constrain(out, seq_axis="mp")
        return _constrain(out, P())


class VocabParallelEmbedding(Layer):
    """Vocab-dim sharded embedding: weight [V, H] spec P('mp', None)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            dtype=self._dtype, default_initializer=I.XavierNormal())
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        from .. import tp_overlap as _tp
        out = F.embedding(x, self.weight)
        if (_tp.layer_schedule(env.get_mesh()) != "gspmd"
                and getattr(out, "ndim", 0) >= 3):
            # sequence-parallel entry: the vocab-sharded lookup's psum lands
            # seq-sharded (a reduce-scatter) instead of replicating [B,S,H]
            return _constrain(out, seq_axis="mp")
        return _constrain(out, P())


class ParallelCrossEntropy(Layer):
    """Softmax-CE over a vocab-sharded logits tensor (ref mp_layers
    ParallelCrossEntropy / c_softmax_with_cross_entropy). GSPMD partitions the
    logsumexp reduction; code is the plain formula on logical shapes. The
    `mp_group` names the mesh axis the vocab dim is sharded over (default
    'mp') — the constraint pins the logits layout so the reduction is
    actually partitioned instead of silently replicated."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        self.mp_group = mp_group

    def forward(self, input, label):
        axis = _group_axis(self.mp_group)
        mesh = env.get_mesh()
        # only pin the layout when the mesh actually has a >1 axis of that
        # name — a constraint naming a missing axis fails at trace time,
        # and dp-only meshes are a supported configuration here
        if mesh is not None and mesh.shape.get(axis, 0) > 1:
            input = _constrain(input, last_axis=axis)
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


def _group_axis(group, default="mp"):
    if group is None:
        return default
    if isinstance(group, str):
        return group
    return getattr(group, "axis_name", None) or default


def split(x, num_or_sections, axis=0, group=None):
    """paddle.distributed.split parity for tensor splitting across the mp
    group — the TPU model keeps logical tensors, so a valid split request
    returns the input annotated with the matching sharding (GSPMD
    partitions dim `axis` over the group's mesh axis). Invalid requests
    raise instead of being silently ignored."""
    shape = tuple(x.shape)
    ndim = len(shape)
    if not isinstance(axis, int):
        raise TypeError(f"split axis must be an int, got {type(axis).__name__}")
    if not (-ndim <= axis < ndim):
        raise ValueError(f"split axis {axis} out of range for rank {ndim}")
    axis = axis % ndim
    dim = int(shape[axis])
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if n <= 0:
            raise ValueError(f"num_or_sections must be positive, got {n}")
        if dim % n:
            raise ValueError(
                f"dim {dim} of axis {axis} not divisible into {n} sections")
    elif isinstance(num_or_sections, (list, tuple)):
        if not num_or_sections or sum(num_or_sections) != dim:
            raise ValueError(
                f"sections {list(num_or_sections)} must sum to dim {dim}")
        if len(set(num_or_sections)) != 1:
            raise ValueError(
                "sharded split needs equal sections (a mesh axis partitions "
                f"evenly), got {list(num_or_sections)}")
        n = len(num_or_sections)
    else:
        raise TypeError("num_or_sections must be an int or a list/tuple, "
                        f"got {type(num_or_sections).__name__}")
    mesh = env.get_mesh()
    ax_name = _group_axis(group)
    if mesh is None or mesh.shape.get(ax_name, 1) <= 1:
        return x  # single-chip view: validated, identity
    if n != mesh.shape[ax_name]:
        import warnings
        warnings.warn(
            f"split into {n} sections does not match mesh axis "
            f"{ax_name!r} of size {mesh.shape[ax_name]}; returning the "
            f"input unannotated")
        return x
    spec = P(*[ax_name if i == axis else None for i in range(ndim)])
    return _constrain(x, spec)


def mp_allreduce(x, group=None):
    from ..collective import all_reduce
    return all_reduce(x, group=group or "mp")
