"""Fleet — hybrid-parallel orchestration.

Re-design of python/paddle/distributed/fleet (fleet.py, meta_parallel/*):
`fleet.init` builds the hybrid device mesh (pp × dp × sharding × sp × mp) from
DistributedStrategy.hybrid_configs; `distributed_model` annotates parameter
PartitionSpecs (ZeRO weight sharding) and returns the model;
`distributed_optimizer` tags the optimizer with the sharding stage so
TrainStep shards optimizer slots over the 'sharding' axis. The actual
communication is emitted by XLA from these annotations — there is no runtime
process-group layer to manage.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import env
from ..env import create_hybrid_mesh, get_mesh
from . import mp_layers  # noqa: F401
from . import utils  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from ..pipeline import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: F401


class DistributedStrategy:
    """ref: python/paddle/distributed/fleet/base/distributed_strategy.py."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.sharding = False
        self.sharding_configs = {"stage": 1, "degree": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        # ref: fleet/meta_optimizers/lars_optimizer.py:23 / dgc_optimizer.py
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001,
                             "lars_weight_decay": 0.0005,
                             "exclude_from_weight_decay": [], "epsilon": 0}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        # localsgd / fp16_allreduce (ref: fleet/meta_optimizers/
        # localsgd_optimizer.py, fp16_allreduce_optimizer.py): both exist to
        # cut NCCL allreduce cost. Under GSPMD the gradient reduction is
        # compiler-emitted from shardings, so the faithful mappings are:
        #   fp16_allreduce -> amp O2 (bf16 grads => bf16 collective payload)
        #   localsgd       -> gradient_merge (k-step local accumulation
        #                     before the fused reduce+update)
        # Setting these flags warns with that mapping instead of silently
        # doing nothing.
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.fp16_allreduce = False
        self.find_unused_parameters = False


class _HybridCommunicateGroup:
    """Topology info accessor (ref: fleet/base/topology.py)."""

    def __init__(self, mesh):
        self._mesh = mesh

    def get_model_parallel_world_size(self):
        return self._mesh.shape.get("mp", 1)

    def get_data_parallel_world_size(self):
        return self._mesh.shape.get("dp", 1)

    def get_pipe_parallel_world_size(self):
        return self._mesh.shape.get("pp", 1)

    def get_sharding_parallel_world_size(self):
        return self._mesh.shape.get("sharding", 1)

    def get_model_parallel_group(self):
        from ..collective import Group
        return Group("mp")

    def get_data_parallel_group(self):
        from ..collective import Group
        return Group("dp")

    def get_pipe_parallel_group(self):
        from ..collective import Group
        return Group("pp")

    def get_sharding_parallel_group(self):
        from ..collective import Group
        return Group("sharding")

    # single-controller: rank-style accessors report coordinate 0 views
    def get_model_parallel_rank(self):
        return 0

    def get_data_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._zero_stage = 0

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level=0):
        self._strategy = strategy or DistributedStrategy()
        env.init_parallel_env()
        hc = self._strategy.hybrid_configs
        n = jax.device_count()
        mp = hc.get("mp_degree", 1)
        pp = hc.get("pp_degree", 1)
        sh = hc.get("sharding_degree", 1)
        sp = hc.get("sep_degree", 1)
        dp = hc.get("dp_degree", 1)
        if mp * pp * sh * sp * dp != n:
            dp = -1  # absorb the remainder into dp, reference does the same
        mesh = create_hybrid_mesh(dp=dp, mp=mp, pp=pp, sharding=sh, sp=sp)
        self._hcg = _HybridCommunicateGroup(mesh)
        if self._strategy.sharding:
            self._zero_stage = int(self._strategy.sharding_configs.get("stage", 1))
        return self

    @property
    def strategy(self):
        return self._strategy

    def get_hybrid_communicate_group(self):
        return self._hcg

    def worker_num(self):
        return jax.process_count()

    def worker_index(self):
        return jax.process_index()

    def is_first_worker(self):
        return jax.process_index() == 0

    def barrier_worker(self):
        pass

    def distributed_model(self, model):
        """Annotate params for the active parallel axes. TP layers already
        carry specs; ZeRO stage-3 additionally shards every remaining param's
        largest dim over 'sharding'."""
        mesh = get_mesh()
        if mesh is None:
            return model
        if self._zero_stage >= 3 and mesh.shape.get("sharding", 1) > 1:
            for _, p in model.named_parameters():
                if p.dist_spec is not None:
                    continue
                shape = tuple(p.shape)
                if not shape:
                    continue
                axis = max(range(len(shape)), key=lambda i: shape[i])
                if shape[axis] % mesh.shape["sharding"] == 0:
                    spec = [None] * len(shape)
                    spec[axis] = "sharding"
                    p.dist_spec = P(*spec)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        strategy = strategy or self._strategy
        # lars/dgc swap FIRST so the zero/gradient-merge attributes below
        # land on the optimizer that will actually run
        from ...optimizer import Momentum
        from ...optimizer.meta import LarsMomentum, DGCMomentum
        if strategy is not None and getattr(strategy, "lars", False) \
                and isinstance(optimizer, Momentum):
            # ref: lars_optimizer.py:23 — swap a Momentum inner optimizer
            # for LarsMomentum per strategy.lars_configs
            cfg = strategy.lars_configs
            optimizer = LarsMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                exclude_from_weight_decay=cfg.get(
                    "exclude_from_weight_decay", []),
                epsilon=cfg.get("epsilon", 0),
                grad_clip=optimizer._grad_clip)
        elif strategy is not None and getattr(strategy, "dgc", False) \
                and isinstance(optimizer, Momentum):
            # ref: dgc_optimizer.py:444
            cfg = strategy.dgc_configs
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", [0.999]),
                use_nesterov=optimizer._nesterov,
                grad_clip=optimizer._grad_clip)
        import warnings
        if strategy is not None and getattr(strategy, "localsgd", False):
            k = int(strategy.localsgd_configs.get("k_steps", 1))
            warnings.warn(
                "strategy.localsgd maps to gradient_merge on this backend "
                "(GSPMD emits the reduction; k-step local accumulation is "
                f"the compiled analog) — applying k_steps={k}; begin_step "
                "is ignored (accumulation starts immediately)")
            optimizer._gradient_merge_k = max(
                k, int(getattr(optimizer, "_gradient_merge_k", 1)))
        if strategy is not None and getattr(strategy, "fp16_allreduce", False):
            warnings.warn(
                "strategy.fp16_allreduce maps to amp O2 on this backend: "
                "bf16 gradients make the compiler-emitted collective carry "
                "16-bit payloads — use paddle.amp.decorate(level='O2')")
        optimizer._zero_stage = self._zero_stage
        optimizer._shard_opt_states_axis = (
            "sharding" if self._zero_stage >= 1 and
            (get_mesh() and get_mesh().shape.get("sharding", 1) > 1) else None)
        if strategy is not None and getattr(strategy, "gradient_merge", False):
            # ref: fleet/meta_optimizers/gradient_merge_optimizer.py —
            # TrainStep fuses the k-step accumulation into the compiled
            # step. max() so a larger localsgd k is not silently clobbered.
            optimizer._gradient_merge_k = max(
                int(strategy.gradient_merge_configs.get("k_steps", 1)),
                int(getattr(optimizer, "_gradient_merge_k", 1)))
        return optimizer


_fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None, log_level=0):
    return _fleet.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return _fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _fleet.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group():
    return _fleet.get_hybrid_communicate_group()


def worker_num():
    return _fleet.worker_num()


def worker_index():
    return _fleet.worker_index()


def is_first_worker():
    return _fleet.is_first_worker()


fleet = _fleet
