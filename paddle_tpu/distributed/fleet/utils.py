"""fleet.utils (ref: python/paddle/distributed/fleet/utils/__init__.py —
exports LocalFS, recompute, HDFSClient, DistributedInfer; fs.py for the FS
classes).

The NCCL-era gradient helpers (hybrid_parallel_util._apply_collective_grads
etc.) have no analog: GSPMD emits those collectives from sharding
annotations. The filesystem abstraction and recompute re-export are the
user-facing surface and live here.
"""
from __future__ import annotations

import os
import shutil
import subprocess

from ..recompute import recompute  # noqa: F401  (ref utils/__init__.py:31)


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FSShellCmdAborted(ExecuteError):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False, test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem client (ref fs.py LocalFS)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, entry)):
                dirs.append(entry)
            else:
                files.append(entry)
        return dirs, files

    def mkdirs(self, fs_path):
        assert not os.path.isfile(fs_path), f"{fs_path} is already a file"
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            os.remove(fs_path)
        else:
            shutil.rmtree(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, fs_src_path, fs_dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        if self.is_exist(fs_dst_path):
            raise FSFileExistsError(fs_dst_path)
        os.rename(fs_src_path, fs_dst_path)

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [e for e in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, e))]

    def cat(self, fs_path=None):
        with open(fs_path, "r") as f:
            return f.read()


class HDFSClient(FS):
    """Shells out to the hadoop CLI like the reference (ref fs.py
    HDFSClient). Raises at construction when no hadoop binary exists —
    TPU hosts typically read from GCS/local instead."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else shutil.which("hadoop")
        if self._hadoop is None or not os.path.exists(self._hadoop):
            raise RuntimeError(
                "HDFSClient needs a hadoop installation (hadoop_home or "
                "`hadoop` on PATH); none found on this host")
        self._configs = configs or {}
        self._time_out = time_out
        self._sleep_inter = sleep_inter

    def _run(self, *args, retries=2):
        import time as _time
        conf = [f"-D{k}={v}" for k, v in self._configs.items()]
        cmd = [self._hadoop, "fs"] + conf + list(args)
        last = None
        for attempt in range(retries + 1):
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=self._time_out / 1000.0)
            except subprocess.TimeoutExpired as e:
                raise FSTimeOut(f"{' '.join(cmd)} timed out") from e
            if proc.returncode == 0:
                return proc.stdout
            last = ExecuteError(f"{' '.join(cmd)}: {proc.stderr[:400]}")
            if attempt < retries:
                _time.sleep(self._sleep_inter / 1000.0)
        raise last

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    # -test's nonzero exit IS the answer — no retries, no sleeps
    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path, retries=0)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path, retries=0)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path, retries=0)
            return True
        except ExecuteError:
            return False

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", fs_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False, test_exists=False):
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def need_upload_download(self):
        return True

    def cat(self, fs_path=None):
        return self._run("-cat", fs_path)


class DistributedInfer:
    """Parameter-server-era sparse-table inference helper — superseded by
    sharded SPMD inference on TPU (ref utils/ps_util.py DistributedInfer)."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "DistributedInfer targets parameter-server sparse tables; use "
            "paddle_tpu.inference (StableHLO artifacts) with a sharded mesh")
