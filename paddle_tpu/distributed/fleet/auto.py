"""`paddle.distributed.fleet.auto` — user-facing auto-parallel namespace
(ref: python/paddle/distributed/fleet/__init__.py exposes `auto` as the
semi-auto API: Engine/Strategy plus the dygraph shard_* interface)."""
from ..auto_parallel_static import Engine, Strategy  # noqa: F401
from ..auto_parallel import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, reshard,
    shard_layer, shard_optimizer, dtensor_from_fn, dtensor_from_local,
    to_static, DistModel,
)

fetch = None  # the reference's fetch-collection hook has no XLA analog

__all__ = [
    "Engine", "Strategy", "ProcessMesh", "Shard", "Replicate", "Partial",
    "shard_tensor", "reshard", "shard_layer", "shard_optimizer",
    "dtensor_from_fn", "dtensor_from_local", "to_static", "DistModel",
]
