"""Collective communication API (ref: python/paddle/distributed/communication/*).

Two execution contexts:
  * inside an SPMD region (shard_map / pjit-manual): lowers to XLA collectives
    (`psum`, `all_gather`, `ppermute`, `all_to_all`) over the named mesh axis —
    the ICI path, this is where training-time communication happens;
  * eager, single controller: tensors are global (the SPMD model has no
    per-rank eager view), so SUM-like collectives are identity when
    world_size==1 and otherwise interpreted as "already reduced" — matching
    how the reference's API behaves after gradient sync.

Groups are named mesh axes (default: all axes of the active mesh).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..tensor_impl import Tensor, as_tensor_data
from ..dispatch import apply as _apply
from . import env


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = a named mesh axis (or tuple of axes)."""

    def __init__(self, axis_name, ranks=None):
        self.axis_name = axis_name
        self.ranks = ranks
        self.nranks = len(ranks) if ranks else None

    @property
    def name(self):
        return str(self.axis_name)

    def __repr__(self):
        return f"Group(axis={self.axis_name})"


_default_group = Group("dp")


def new_group(ranks=None, backend=None, axis_name=None):
    return Group(axis_name or "dp", ranks)


def get_group(gid=0):
    return _default_group


def _axis(group):
    if group is None:
        return _default_group.axis_name
    if isinstance(group, Group):
        return group.axis_name
    return group  # allow raw axis name strings


def _in_spmd(axis_name):
    """True when called under shard_map with this axis bound."""
    try:
        return axis_name in jax.core.get_axis_env().axis_sizes  # jax>=0.8 internal
    except Exception:
        try:
            lax.axis_index(axis_name)
            return True
        except Exception:
            return False


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis(group)
    if _in_spmd(axis):
        fn = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax, ReduceOp.MIN: lax.pmin,
              ReduceOp.AVG: lax.pmean}.get(op)
        if op == ReduceOp.PROD:
            def fn(x, a):
                # sign-and-magnitude lowering: exp(psum(log|x|)) for the
                # magnitude with zeros masked to 1, sign from the parity of
                # the negative count, exact 0 when any member holds a 0 —
                # the naive exp(psum(log(x))) NaNs on zero/negative inputs
                # float64 magnitude when x64 is enabled (silently float32
                # otherwise): int32+ products overflow fp32's 24-bit mantissa
                xf = x.astype(jnp.float64)
                zeros = lax.psum((xf == 0).astype(jnp.int32), a)
                negs = lax.psum((xf < 0).astype(jnp.int32), a)
                mag = jnp.exp(lax.psum(
                    jnp.log(jnp.where(xf == 0, 1.0, jnp.abs(xf))), a))
                sign = jnp.where(negs % 2 == 0, 1.0, -1.0)
                res = jnp.where(zeros > 0, 0.0, sign * mag)
                if jnp.issubdtype(x.dtype, jnp.integer):
                    # exp/log round-trip is inexact; truncation toward zero
                    # would turn prod([2, 3]) = 5.9999995 into 5
                    res = jnp.round(res)
                return res.astype(x.dtype)
        out = _apply(lambda x: fn(x, axis), tensor, op_name="all_reduce")
        if isinstance(tensor, Tensor):
            tensor._data = out._data
            tensor._node = out._node
            tensor._out_idx = out._out_idx
            return tensor
        return out
    return tensor  # global view: already reduced


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """Both reference signatures: all_gather(list, t) and functional return."""
    if tensor is None:
        tensor, tensor_list = tensor_list, None
    ax = _axis(group)
    if _in_spmd(ax):
        out = _apply(lambda x: lax.all_gather(x, ax, tiled=True), tensor,
                     op_name="all_gather")
    else:
        out = tensor
    if tensor_list is not None:
        n = env.world_size()
        from ..tensor import manipulation as M
        chunks = M.split(out, n, axis=0) if n > 1 else [out]
        tensor_list.extend(chunks)
        return None
    return out


def all_gather_object(obj_list, obj, group=None):
    obj_list.append(obj)


def reduce_scatter(tensor, tensor_or_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _axis(group)
    src = tensor_or_list if tensor_or_list is not None else tensor
    if _in_spmd(ax):
        def f(x):
            return lax.psum_scatter(x, ax, tiled=True)
        out = _apply(f, src, op_name="reduce_scatter")
        if tensor_or_list is not None and isinstance(tensor, Tensor):
            tensor._data = out._data
            return tensor
        return out
    return src


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = _axis(group)
    if _in_spmd(ax):
        def f(x):
            # take src's value on every member of the axis
            full = lax.all_gather(x, ax)
            return full[src]
        out = _apply(f, tensor, op_name="broadcast")
        if isinstance(tensor, Tensor):
            tensor._data = out._data
            return tensor
        return out
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _axis(group)
    if _in_spmd(ax):
        idx = lax.axis_index(ax)
        if tensor_list is not None:
            from ..tensor import manipulation as M
            stacked = M.stack(tensor_list, axis=0)
            out = _apply(lambda s: s[idx], stacked, op_name="scatter")
        else:
            out = _apply(lambda x: lax.dynamic_index_in_dim(x, idx, keepdims=False),
                         tensor, op_name="scatter")
        if isinstance(tensor, Tensor):
            tensor._data = out._data
            return tensor
        return out
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _axis(group)
    from ..tensor import manipulation as M
    if isinstance(in_tensor_list, (list, tuple)):
        x = M.stack(list(in_tensor_list), axis=0)
    else:
        x = in_tensor_list
    if _in_spmd(ax):
        out = _apply(lambda a: lax.all_to_all(a, ax, split_axis=0, concat_axis=0,
                                              tiled=False), x, op_name="alltoall")
    else:
        out = x
    if out_tensor_list is not None:
        out_tensor_list.extend(list(out))
        return None
    return out


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _axis(group)
    if _in_spmd(ax):
        out = _apply(lambda a: lax.all_to_all(a, ax, split_axis=0, concat_axis=0,
                                              tiled=True), in_tensor,
                     op_name="alltoall")
    else:
        out = in_tensor
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._data = as_tensor_data(out)
        return None
    return out


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point on a ring: implemented as ppermute inside SPMD regions."""
    ax = _axis(group)
    if _in_spmd(ax):
        n = env.axis_size(ax)
        perm = [(i, dst) for i in range(n)]
        return _apply(lambda x: lax.ppermute(x, ax, perm), tensor, op_name="send")
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    ax = _axis(group)
    if _in_spmd(ax):
        n = env.axis_size(ax)
        perm = [(src, i) for i in range(n)]
        out = _apply(lambda x: lax.ppermute(x, ax, perm), tensor, op_name="recv")
        if isinstance(tensor, Tensor):
            tensor._data = out._data
            return tensor
        return out
    return tensor


def p2p_shift(tensor, group=None, shift=1):
    """Ring shift (the TPU-native send/recv pair): every member passes its value
    `shift` steps around the axis. Used by pipeline & ring attention."""
    ax = _axis(group)
    def f(x):
        n = env.axis_size(ax)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, ax, perm)
    return _apply(f, tensor, op_name="p2p_shift")


def barrier(group=None):
    jax.block_until_ready(jnp.zeros(()))


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._data)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # SPMD model has no single-destination reduce; psum everywhere is the
    # TPU-native equivalent (the extra copies are free vs. ICI latency)
    return all_reduce(tensor, op, group, sync_op)


def stream_allreduce(*a, **k):
    return all_reduce(*a, **k)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather to dst (ref communication/gather.py). SPMD model: all_gather
    everywhere (a single-destination gather saves nothing on ICI); eager
    single-controller: every rank holds the same replicated value, so the
    gather list is world_size copies of it."""
    ax = _axis(group)
    if _in_spmd(ax):
        out = _apply(lambda x: lax.all_gather(x, ax), tensor,
                     op_name="gather")
        chunks = [out[i] for i in range(out.shape[0])]
    else:
        # independent copies: aliasing one Tensor world_size times would
        # make any in-place edit of one entry mutate all of them
        chunks = [Tensor(tensor._data) if isinstance(tensor, Tensor)
                  else tensor for _ in range(env.world_size())]
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend(chunks)
    return chunks


def isend(tensor, dst=0, group=None):
    """Async send returns a waitable task (ref communication/isend); under
    the compiled SPMD model dispatch is already async, so the task's wait
    is a device sync."""
    res = send(tensor, dst, group, sync_op=False)

    class _Task:
        def wait(self, *a, **k):
            return wait(res)
    return _Task()


def irecv(tensor, src=0, group=None):
    res = recv(tensor, src, group, sync_op=False)

    class _Task:
        def wait(self, *a, **k):
            return wait(res)
    return _Task()


def broadcast_object_list(object_list, src=0, group=None):
    """Python-object broadcast (ref communication/broadcast.py). The
    single-controller owns every rank's python state, so the list is
    already consistent; kept for API parity."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    out_object_list.clear()
    if in_object_list:
        out_object_list.append(in_object_list[get_rank_in(group)])
    return out_object_list


def get_rank_in(group=None):
    """Rank within `group` (falls back to global rank for the world)."""
    from .env import get_rank
    rank = get_rank()
    ranks = getattr(group, "ranks", None) if group is not None else None
    if ranks:
        return list(ranks).index(rank) if rank in ranks else 0
    return rank


def destroy_process_group(group=None):
    """Reset mesh/env state (ref communication/group.py destroy)."""
    from . import env as _env
    if group is None:
        _env.set_mesh(None)


def is_available():
    return True
