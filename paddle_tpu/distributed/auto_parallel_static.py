"""Semi-auto parallel static `Engine` — the reference's flagship entry point
(ref: python/paddle/distributed/auto_parallel/static/engine.py:55 Engine,
auto_parallel/strategy.py Strategy).

The reference Engine builds a serial Program, plans a distribution
(Planner), partitions + reshards it (Parallelizer), then drives it with the
StandaloneExecutor. The TPU-native pipeline collapses the middle: the model's
`shard_tensor` placements (dist_spec) ARE the plan, `jax.jit` over the mesh
is partitioner+reshard (GSPMD inserts every collective the reshard pass
would have emitted), and the compiled step is the executor. `Strategy`
toggles map onto compile-time knobs:

    amp            -> auto_cast tracing dtype / O2 param cast
    recompute      -> jax.checkpoint on the loss closure (policy registry)
    gradient_merge -> accumulate_steps fused into the step (lax.cond)
    sharding       -> optimizer-slot ZeRO axis (+ FSDP specs at stage 3)
    pipeline       -> microbatched scan schedule (flagship GPT path)

Two backends behind one API:
  * any `nn.Layer`       -> jit.TrainStep (generic SPMD step)
  * a GPT `GPTConfig`    -> models.gpt_hybrid.HybridTrainStep (the flagship
                            TP x PP x DP x ZeRO path), so `Engine.fit` drives
                            the same program the perf work tunes.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor_impl import Tensor
from . import env


class _Config:
    """Attribute bag mirroring the reference's BaseConfig sub-configs
    (ref: auto_parallel/strategy.py:20)."""

    def __init__(self, **defaults):
        self._fields = list(defaults)
        for k, v in defaults.items():
            setattr(self, k, v)

    def from_dict(self, d):
        for k, v in (d or {}).items():
            setattr(self, k, v)
            if k not in self._fields:
                self._fields.append(k)

    def to_dict(self):
        return {k: getattr(self, k) for k in self._fields}

    def get(self, k, d=None):
        return getattr(self, k, d)

    def __repr__(self):
        return f"_Config({self.to_dict()})"


class Strategy:
    """Parallelization/optimization config (ref: auto_parallel/strategy.py:141).

    >>> s = Strategy()
    >>> s.amp.enable = True
    >>> s.recompute.enable = True
    >>> s.gradient_merge.enable, s.gradient_merge.k_steps = True, 4
    """

    def __init__(self, config=None):
        self.auto_mode = "semi"
        self.seed = None
        self.amp = _Config(enable=False, dtype="bfloat16", level="O1",
                           custom_white_list=None, custom_black_list=None,
                           init_loss_scaling=2.0 ** 16,
                           use_dynamic_loss_scaling=True)
        self.recompute = _Config(enable=False, checkpoints=None,
                                 policy="full")
        self.sharding = _Config(enable=False, stage=1, degree=-1,
                                axis=None, offload=False)
        self.gradient_merge = _Config(enable=False, k_steps=1, avg=True)
        self.pipeline = _Config(enable=False, schedule_mode="1F1B",
                                micro_batch_size=1, accumulate_steps=1,
                                vpp_degree=1)
        self.fused_passes = _Config(enable=False, fused_passes_list=[])
        self.dataset = _Config(enable=False, num_shards=1)
        if config:
            for key, sub in dict(config).items():
                cur = getattr(self, key, None)
                if isinstance(cur, _Config):
                    cur.from_dict(sub)
                else:
                    setattr(self, key, sub)

    def to_dict(self):
        out = {"auto_mode": self.auto_mode, "seed": self.seed}
        for k in ("amp", "recompute", "sharding", "gradient_merge",
                  "pipeline", "fused_passes", "dataset"):
            out[k] = getattr(self, k).to_dict()
        return out


def _as_batch_items(batch):
    if isinstance(batch, dict):
        return list(batch.values())
    if isinstance(batch, (list, tuple)):
        return list(batch)
    return [batch]


def _split_sample(items, split):
    """First `split` items feed the model, the rest are labels (ref:
    engine.py _prepare_data_spec sample_split semantics). split=None: the
    last item is the label when there are >= 2 items."""
    if split is None:
        split = len(items) - 1 if len(items) >= 2 else len(items)
    return items[:split], items[split:]


class Engine:
    """Auto-parallel training/eval/predict driver (ref:
    auto_parallel/static/engine.py:55).

    >>> engine = auto.Engine(model, loss, optimizer, metrics, strategy=s)
    >>> engine.fit(train_dataset, epochs=2, batch_size=64)
    >>> engine.evaluate(valid_dataset, batch_size=64)
    >>> engine.predict(test_dataset, batch_size=64)
    >>> engine.save("./ckpt"); engine.load("./ckpt")
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None, mesh=None):
        from ..models.gpt import GPTConfig
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        if metrics is None:
            metrics = []
        self._metrics = metrics if isinstance(metrics, (list, tuple)) \
            else [metrics]
        self._cluster = cluster
        self._strategy = strategy or Strategy()
        self._mesh = mesh if mesh is not None else env.get_mesh()
        self._mode = "train"
        self._train_step = None
        self._eval_fn = None
        self._predict_fn = None
        self._history = None
        self._is_gpt_config = isinstance(model, GPTConfig)
        if self._strategy.seed is not None:
            from ..framework.random import seed as _seed
            _seed(self._strategy.seed)

    # -- build ---------------------------------------------------------------

    def _accumulate_steps(self):
        s = self._strategy
        k = 1
        if s.gradient_merge.enable:
            k = max(k, int(s.gradient_merge.k_steps))
        if s.pipeline.enable:
            k = max(k, int(s.pipeline.accumulate_steps))
        return k

    def _sharding_axis(self):
        s = self._strategy.sharding
        if not s.enable or self._mesh is None:
            return None
        if s.axis:
            return s.axis if s.axis in self._mesh.axis_names else None
        for cand in ("sharding", "dp", "sdp"):
            if cand in self._mesh.axis_names:
                return cand
        return self._mesh.axis_names[0] if self._mesh.axis_names else None

    def _ensure_train_step(self):
        if self._train_step is not None:
            return
        if self._optimizer is None:
            raise ValueError("Engine needs an optimizer to train "
                             "(ref engine.py: optimizer required in train)")
        s = self._strategy
        axis = self._sharding_axis()
        if axis is not None:
            self._optimizer._shard_opt_states_axis = axis
        if self._is_gpt_config:
            self._train_step = self._build_gpt_step()
            return
        model = self._model
        if s.amp.enable and s.amp.level == "O2":
            from .. import amp as _amp
            model, self._optimizer = _amp.decorate(
                model, self._optimizer, level="O2", dtype=s.amp.dtype)
        from ..jit.train_step import TrainStep
        self._train_step = TrainStep(
            model, self._loss, self._optimizer, mesh=self._mesh,
            remat=bool(s.recompute.enable),
            accumulate_steps=self._accumulate_steps())

    def _build_gpt_step(self):
        """Flagship path: Strategy -> HybridTrainStep knobs. The model IS the
        GPTConfig; pipeline/recompute/amp map onto the hybrid step's config
        fields so Engine.fit drives the exact tuned program."""
        from ..models.gpt_hybrid import HybridTrainStep
        s = self._strategy
        cfg = self._model
        if s.recompute.enable:
            cfg.remat = True
            if s.recompute.policy and s.recompute.policy != "full":
                cfg.remat_policy = s.recompute.policy
        if s.amp.enable:
            cfg.compute_dtype = s.amp.dtype
        if s.sharding.enable and s.sharding.offload:
            self._optimizer._offload_opt_states = True
        if s.gradient_merge.enable and not s.pipeline.enable:
            import warnings
            warnings.warn(
                "Strategy.gradient_merge on the flagship GPT path requires "
                "pipeline microbatching (pipeline.accumulate_steps); the "
                "k_steps setting is not applied to HybridTrainStep")
        num_micro = 1
        if s.pipeline.enable:
            cfg.pp_schedule = {"1F1B": "1f1b", "FThenB": "gpipe",
                               "VPP": "1f1b"}.get(
                                   s.pipeline.schedule_mode, "1f1b")
            if s.pipeline.vpp_degree > 1:
                cfg.pp_interleave = int(s.pipeline.vpp_degree)
            num_micro = max(int(s.pipeline.accumulate_steps), 1)
        zero_stage = int(s.sharding.stage) if s.sharding.enable else 1
        return HybridTrainStep(
            self._model, self._optimizer, mesh=self._mesh,
            num_microbatches=num_micro,
            seed=self._strategy.seed or 0, zero_stage=zero_stage)

    def _make_loader(self, data, batch_size, collate_fn=None, shuffle=False):
        from ..io import DataLoader
        if data is None:
            return None
        if hasattr(data, "__iter__") and not hasattr(data, "__getitem__"):
            return data  # already an iterable loader
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          collate_fn=collate_fn, drop_last=True)

    # -- train ---------------------------------------------------------------

    def fit(self, train_data, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, save_dir=None,
            save_freq=1, valid_data=None, valid_freq=1,
            valid_sample_split=None, valid_steps=None, collate_fn=None,
            callbacks=None, verbose=2, nvprof_range=None):
        """ref: engine.py:854 fit. Returns a history dict of per-epoch logs."""
        self._mode = "train"
        loader = self._make_loader(train_data, batch_size,
                                   collate_fn=collate_fn, shuffle=True)
        history = {"loss": []}
        for epoch in range(epochs):
            losses = []
            for step_i, batch in enumerate(loader):
                if steps_per_epoch is not None and step_i >= steps_per_epoch:
                    break
                loss = self.run(batch, mode="train",
                                sample_split=train_sample_split)
                losses.append(float(np.asarray(loss)))
                if verbose and log_freq and (step_i + 1) % log_freq == 0:
                    print(f"epoch {epoch} step {step_i + 1}: "
                          f"loss {losses[-1]:.6f}")
            epoch_loss = float(np.mean(losses)) if losses else float("nan")
            history["loss"].append(epoch_loss)
            if verbose:
                print(f"epoch {epoch}: loss {epoch_loss:.6f}")
            if valid_data is not None and (epoch + 1) % valid_freq == 0:
                logs = self.evaluate(valid_data, batch_size=batch_size,
                                     steps=valid_steps,
                                     valid_sample_split=valid_sample_split,
                                     verbose=0)
                for k, v in logs.items():
                    history.setdefault("val_" + k, []).append(v)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, f"epoch{epoch}"))
        self._history = history
        return history

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, collate_fn=None, callbacks=None, verbose=2):
        """ref: engine.py:1025 evaluate. Returns {"loss": ..., metric: ...}."""
        self._mode = "eval"
        loader = self._make_loader(valid_data, batch_size,
                                   collate_fn=collate_fn)
        for m in self._metrics:
            m.reset()
        losses = []
        for step_i, batch in enumerate(loader):
            if steps is not None and step_i >= steps:
                break
            items = [self._to_array(x) for x in _as_batch_items(batch)]
            inputs, labels = _split_sample(items, valid_sample_split)
            loss, outs = self._run_eval(tuple(inputs), tuple(labels))
            losses.append(float(np.asarray(loss)))
            self._update_metrics(outs, labels)
        logs = {"loss": float(np.mean(losses)) if losses else float("nan")}
        for m in self._metrics:
            logs[m.name() if callable(getattr(m, "name", None)) else str(m)] \
                = m.accumulate()
        if verbose:
            print("eval:", logs)
        return logs

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=2):
        """ref: engine.py:1136 predict. Returns the list of per-batch
        forward outputs (numpy)."""
        self._mode = "predict"
        loader = self._make_loader(test_data, batch_size,
                                   collate_fn=collate_fn)
        outputs = []
        for step_i, batch in enumerate(loader):
            if steps is not None and step_i >= steps:
                break
            items = [self._to_array(x) for x in _as_batch_items(batch)]
            inputs, _ = _split_sample(items, test_sample_split)
            out = self._run_forward(tuple(inputs))
            outputs.append(jax.tree_util.tree_map(
                lambda a: np.asarray(jax.device_get(a)), out))
        return outputs

    def dataloader(self, dataset, batch_size=1, shuffle=False,
                   collate_fn=None, num_workers=0, sample_split=None,
                   mode="train"):
        """Build the loader the engine will consume (ref: engine.py:1234
        dataloader). On this backend there is no distributed reader
        transformation — batches enter the compiled step and GSPMD scatters
        them per the data sharding."""
        self._mode = mode
        return self._make_loader(dataset, batch_size, collate_fn=collate_fn,
                                 shuffle=shuffle and mode == "train")

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Pre-build the compiled step from InputSpecs (ref: engine.py:1320
        prepare): trace/compile happens now instead of on the first batch."""
        self._mode = mode
        if mode != "train":
            return self
        if inputs_spec is None:
            raise ValueError("prepare() needs inputs_spec")
        to_list = lambda s: list(s) if isinstance(s, (list, tuple)) else [s]  # noqa: E731
        zeros = [np.zeros([d or 1 for d in spec.shape],
                          getattr(spec, "dtype", "float32"))
                 for spec in to_list(inputs_spec)]
        zlabels = [np.zeros([d or 1 for d in spec.shape],
                            getattr(spec, "dtype", "float32"))
                   for spec in to_list(labels_spec or [])]
        self.run(zeros + zlabels, mode="train",
                 sample_split=len(zeros))
        return self

    # -- single-step execution (ref: engine.py:1376 run) ---------------------

    def run(self, data=None, feed=None, fetch_list=None, mode=None,
            sample_split=None):
        mode = mode or self._mode
        items = [self._to_array(x) for x in _as_batch_items(
            data if data is not None else feed)]
        inputs, labels = _split_sample(items, sample_split)
        if mode == "train":
            self._ensure_train_step()
            s = self._strategy
            if self._is_gpt_config:
                return self._train_step(inputs[0])
            if s.amp.enable and s.amp.level in ("O1", "OD"):
                from .. import amp as _amp
                with _amp.auto_cast(level=s.amp.level, dtype=s.amp.dtype,
                                    custom_white_list=s.amp.custom_white_list,
                                    custom_black_list=s.amp.custom_black_list):
                    return self._train_step(tuple(inputs), tuple(labels))
            return self._train_step(tuple(inputs), tuple(labels))
        if mode == "eval":
            loss, _ = self._run_eval(tuple(inputs), tuple(labels))
            return loss
        return self._run_forward(tuple(inputs))

    def _to_array(self, x):
        if isinstance(x, Tensor):
            return x._data
        return jnp.asarray(x)

    def _run_eval(self, inputs, labels):
        if self._is_gpt_config:
            self._ensure_train_step()
            return self._train_step.loss_only(inputs[0]), None
        if self._eval_fn is None:
            self._ensure_train_step()
            # trigger compile of the train path lazily only if never trained;
            # eval shares its param capture
            if self._train_step._jitted is None:
                # params exist pre-compile; build_eval needs sample shapes
                self._train_step._sample_inputs = inputs
                self._train_step._sample_labels = labels
            self._eval_fn = self._train_step.build_eval()
        ts = self._train_step
        return self._eval_fn(ts._params, ts._buffers, inputs, labels)

    def _run_forward(self, inputs):
        if self._is_gpt_config:
            from ..models.gpt_hybrid import gpt_forward
            self._ensure_train_step()
            ts = self._train_step
            return gpt_forward(ts.params, inputs[0], self._model,
                               ts.mesh, ts.num_microbatches)
        if self._predict_fn is None:
            self._ensure_train_step()
            from ..jit.functional import functional_call

            def fwd(params, buffers, ins):
                out, _ = functional_call(self._model, params, buffers, ins)
                return out
            self._predict_fn = jax.jit(fwd)
        ts = self._train_step
        return self._predict_fn(ts._params, ts._buffers, inputs)

    def _update_metrics(self, outs, labels):
        if outs is None or not self._metrics:
            return
        from ..framework import state as _st
        with _st.functional_trace():
            out_t = jax.tree_util.tree_map(Tensor, outs)
            lab_t = [Tensor(l) for l in labels]
            for m in self._metrics:
                if hasattr(m, "compute"):
                    r = m.compute(out_t if not isinstance(out_t, (list, tuple))
                                  else out_t[0], *lab_t)
                    m.update(np.asarray(r._data if isinstance(r, Tensor)
                                        else r))
                else:
                    m.update(out_t, lab_t)

    # -- io ------------------------------------------------------------------

    def save(self, path, training=True):
        """ref: engine.py:1621. Saves params (+ optimizer state when
        training=True) via the checkpoint layer."""
        from ..framework import io as fio
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if self._train_step is None:
            state = {"params": {}, "step": 0}
        elif self._is_gpt_config:
            ts = self._train_step
            host = lambda tree: jax.tree_util.tree_map(  # noqa: E731
                lambda a: np.asarray(jax.device_get(a)), tree)
            state = {"params": host(ts._flat(ts.params)),
                     "opt_state": host(ts.opt_state),
                     "step": ts._step_count}
        else:
            state = self._train_step.state_for_checkpoint()
        if not training:
            state.pop("opt_state", None)
        fio.save(state, path + ".pdparams")

    def load(self, path, strict=True, load_optimizer=True):
        """ref: engine.py:1705."""
        from ..framework import io as fio
        state = fio.load(path + ".pdparams")
        self._ensure_train_step()
        if not load_optimizer:
            state.pop("opt_state", None)
        if self._is_gpt_config:
            ts = self._train_step
            flat = state["params"]
            if isinstance(flat, dict) and set(flat) == set(ts._names):
                ts.params = ts._unflat({n: jnp.asarray(a)
                                        for n, a in flat.items()})
            else:  # a full nested pytree saved by other tooling
                ts.params = jax.tree_util.tree_map(jnp.asarray, flat)
            if load_optimizer and "opt_state" in state:
                ts.opt_state = jax.tree_util.tree_map(jnp.asarray,
                                                      state["opt_state"])
            if ts.mesh is not None:
                ts._place()
        else:
            self._train_step.restore_from_checkpoint(
                {**{"params": state.get("params", {}),
                    "opt_state": state.get("opt_state",
                                           self._train_step._opt_state),
                    "buffers": state.get("buffers", {}),
                    "step": state.get("step", 0)}})
        return self

    # -- introspection -------------------------------------------------------

    def cost(self, inputs_spec=None, labels_spec=None, mode=None):
        """XLA cost analysis of the compiled step (the reference estimates
        via its cost model; here the compiler reports measured numbers).
        Returns (flops_per_step, memory_analysis) — ref: engine.py:1757."""
        if self._train_step is None or getattr(self._train_step, "_jitted",
                                               None) is None:
            return None, None
        jitted = self._train_step._jitted
        try:
            if self._is_gpt_config:
                return None, None
            ts = self._train_step
            lowered = jitted.lower(
                ts._params, ts._opt_state, ts._buffers,
                jnp.zeros((), jnp.float32), jax.random.key(0),
                ts._sample_inputs, ts._sample_labels)
            compiled = lowered.compile()
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            return ca.get("flops"), compiled.memory_analysis()
        except Exception:  # noqa: BLE001 — introspection is best-effort
            return None, None

    @property
    def main_program(self):
        """HLO of the compiled train step (Program analog)."""
        ts = self._train_step
        if ts is None or getattr(ts, "_jitted", None) is None:
            return None
        return "<compiled XLA SPMD train step>"

    @property
    def serial_main_program(self):
        return self.main_program

    @property
    def history(self):
        return self._history

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def strategy(self):
        return self._strategy

    @property
    def mode(self):
        return self._mode

    def to_mode(self, mode):
        self._mode = mode
